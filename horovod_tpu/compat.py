"""JAX version-compatibility shims.

The codebase targets the modern ``jax.shard_map`` API (keyword-only
``mesh``/``in_specs``/``out_specs`` plus the ``check_vma`` flag).  Older
installed JAX releases (≤ 0.4.x) only ship the experimental spelling
``jax.experimental.shard_map.shard_map`` whose replication check is named
``check_rep``.  Every module in this repo imports ``shard_map`` from here so
the whole package loads — and behaves identically — on either API.

Usage::

    from horovod_tpu.compat import shard_map     # instead of `from jax import shard_map`
"""

from __future__ import annotations

import functools

try:
    from jax import shard_map as _shard_map          # JAX ≥ 0.6 public API
    _HAS_CHECK_VMA = True
except ImportError:                                  # JAX ≤ 0.4/0.5 fallback
    from jax.experimental.shard_map import shard_map as _shard_map
    _HAS_CHECK_VMA = False

try:
    from jax.lax import axis_size                    # JAX ≥ 0.5
except ImportError:
    import jax.core as _jax_core

    def axis_size(axis_name):
        """Size of a bound mesh axis (old-JAX fallback).

        ``jax.core.axis_frame`` returns the bound size and raises
        ``NameError`` for an unbound name — the same contract as the modern
        ``jax.lax.axis_size``.  Tuples of names multiply, matching psum-over-
        multiple-axes semantics.
        """
        if isinstance(axis_name, (tuple, list)):
            n = 1
            for a in axis_name:
                n *= _jax_core.axis_frame(a)
            return n
        return _jax_core.axis_frame(axis_name)


def set_host_device_count(n: int):
    """Declare ``n`` virtual CPU devices — portably, BEFORE backend init.

    New JAX spells this ``jax.config.update("jax_num_cpu_devices", n)``;
    0.4.x does not know that option and only honors the
    ``--xla_force_host_platform_device_count`` XLA flag.  Either way it
    must run before the CPU backend initializes (first ``jax.devices()``
    etc.); an already-initialized backend keeps its device count and this
    call has no effect on it.
    """
    import os

    import jax
    # Always strip any stale count flag first, even when the config path
    # below succeeds: an inherited --xla_force_host_platform_device_count
    # (e.g. a parent harness that stacked its own flags into XLA_FLAGS
    # before spawning us) would otherwise override the config option at
    # backend init and silently pin the OLD count.  Stripping makes
    # stacked callers compose — last caller before backend init wins.
    flags = [f for f in os.environ.get("XLA_FLAGS", "").split()
             if "xla_force_host_platform_device_count" not in f]
    try:
        jax.config.update("jax_num_cpu_devices", int(n))
        os.environ["XLA_FLAGS"] = " ".join(flags)
        return
    except Exception:  # noqa: BLE001 - option unknown on jax <= 0.4.x
        pass
    flags.append(f"--xla_force_host_platform_device_count={int(n)}")
    os.environ["XLA_FLAGS"] = " ".join(flags)


def tpu_compiler_params(**kwargs):
    """Pallas-TPU compiler params across the rename.

    New JAX spells it ``pltpu.CompilerParams``; 0.4.x spells it
    ``pltpu.TPUCompilerParams``.  Same fields either way.
    """
    from jax.experimental.pallas import tpu as pltpu
    cls = getattr(pltpu, "CompilerParams", None) or pltpu.TPUCompilerParams
    return cls(**kwargs)


def abstract_mesh(axis_sizes, axis_names):
    """``jax.sharding.AbstractMesh`` across its signature change.

    New JAX takes ``(axis_sizes, axis_names)``; 0.4.x takes one
    ``((name, size), ...)`` shape tuple.
    """
    from jax.sharding import AbstractMesh
    try:
        return AbstractMesh(tuple(axis_sizes), tuple(axis_names))
    except TypeError:
        return AbstractMesh(tuple(zip(axis_names, axis_sizes)))


def jax_export():
    """The ``jax.export`` module, importable on both old and new JAX.

    Old JAX does not auto-import the submodule, so bare ``jax.export.export``
    raises ``AttributeError`` unless something imported it first.
    """
    import jax.export as _export
    return _export


@functools.wraps(_shard_map)
def shard_map(f, *args, **kwargs):
    """``jax.shard_map`` with the replication-check flag translated.

    Accepts either ``check_vma`` (new spelling) or ``check_rep`` (old) and
    forwards whichever the underlying JAX understands.  Positional
    ``mesh``/``in_specs``/``out_specs`` are passed through untouched.
    """
    if _HAS_CHECK_VMA:
        if "check_rep" in kwargs:
            kwargs["check_vma"] = kwargs.pop("check_rep")
    else:
        if "check_vma" in kwargs:
            kwargs["check_rep"] = kwargs.pop("check_vma")
    return _shard_map(f, *args, **kwargs)
