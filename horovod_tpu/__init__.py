"""horovod_tpu — TPU-native distributed training with Horovod's capabilities.

A brand-new, TPU-first framework (see SURVEY.md for the reference analysis):
XLA collectives over ICI as the data plane, a background coordinator with
tensor fusion / response caching / timeline / stall detection as the control
plane, ``DistributedOptimizer``-family APIs for JAX and PyTorch, an
ICI-topology-aware launcher, and elastic training.

The top-level module mirrors the reference's ``import horovod.torch as hvd``
surface so users can write ``import horovod_tpu as hvd``:

    hvd.init()
    hvd.rank(), hvd.size(), hvd.local_rank()
    hvd.allreduce(x), hvd.allgather(x), hvd.broadcast(x, root_rank=0)
    hvd.alltoall(x), hvd.reducescatter(x), hvd.grouped_allreduce(xs)
    hvd.DistributedOptimizer(...), hvd.broadcast_parameters(...)
"""

__version__ = "0.1.0"

from .common.basics import (  # noqa: F401
    init, shutdown, is_initialized,
    rank, size, local_rank, local_size, cross_rank, cross_size,
    mesh, is_homogeneous,
    add_process_set, remove_process_set, process_set_included,
    xla_built, nccl_built, mpi_enabled, gloo_enabled, mpi_threads_supported,
    cuda_built, rocm_built, tpu_available,
    start_timeline, stop_timeline, start_profile, stop_profile, profile_step,
    NotInitializedError,
)
from .common.process_sets import ProcessSet, global_process_set  # noqa: F401
from .ops.collectives import (  # noqa: F401
    ReduceOp, Average, Sum, Adasum, Min, Max, Product,
)
from .ops.eager import (  # noqa: F401
    allreduce, allreduce_async,
    grouped_allreduce, grouped_allreduce_async,
    grouped_allgather, grouped_allgather_async,
    grouped_reducescatter, grouped_reducescatter_async,
    allgather, allgather_async,
    broadcast, broadcast_async, broadcast_object, allgather_object,
    alltoall, alltoall_async,
    reducescatter, reducescatter_async,
    synchronize, poll, barrier, join,
    stack_per_rank, replicated, to_local, to_global,
)
from . import ops  # noqa: F401
from .jax.optimizer import (  # noqa: F401
    DistributedOptimizer, DistributedGradientTape,
    broadcast_parameters, broadcast_optimizer_state, allreduce_gradients,
)
from .jax.compression import Compression  # noqa: F401
from . import elastic  # noqa: F401
from . import callbacks  # noqa: F401
from . import checkpoint  # noqa: F401
from . import data  # noqa: F401
from . import analysis  # noqa: F401  (collective-correctness analyzer)
