"""``torovodrun`` console entry point.

Equivalent of the reference's ``horovod/runner/launch.py`` (SURVEY.md §2b P7,
§3.3).  The full launcher (arg surface, hostfile parsing, rendezvous server,
ssh/local spawn, elastic driver) lives in this package; this module wires the
CLI.  Currently implements localhost multi-process launch; the TPU-pod
ssh/metadata path follows the same spawn interface.
"""

from __future__ import annotations

import sys


def run_commandline(argv=None) -> int:
    from .run import main
    return main(argv if argv is not None else sys.argv[1:])


if __name__ == "__main__":
    sys.exit(run_commandline())
