"""Per-host bootstrap probe entry point (reference:
``horovod/runner/task/__main__.py`` task service — SURVEY.md P8).

Launched by the driver on every host (directly or over ssh) BEFORE the
workers: reports NICs, then participates in the mutual connectivity check.
Deliberately imports nothing heavy (no jax/tf) so it starts fast.
"""

from __future__ import annotations

import argparse
import sys


def main(argv=None) -> int:
    p = argparse.ArgumentParser(prog="task_probe")
    p.add_argument("--driver-addr", required=True)
    p.add_argument("--driver-port", type=int, required=True)
    p.add_argument("--label", required=True)
    p.add_argument("--nic", default=None)
    args = p.parse_args(argv)
    from .bootstrap import probe_main
    return probe_main(args.driver_addr, args.driver_port, args.label,
                      args.nic)


if __name__ == "__main__":
    sys.exit(main())
