"""``torovodrun`` argument surface and launch orchestration.

Parity with the reference launcher (``horovod/runner/launch.py``, ``run.py``,
``gloo_run.py``, ``mpi_run.py`` — SURVEY.md §2b P7, §3.3): parse
``-np``/``-H``/``--hostfile``/elastic/timeline/autotune/fusion flags (plus
``--config-file`` YAML mirroring them), compute the rank→host placement, and
spawn per-rank worker processes with the ``HOROVOD_*`` environment injected.

TPU-first differences:
- No mpirun backend: workers are spawned directly (localhost) or over ssh,
  and the distributed world is formed by ``jax.distributed`` against the
  launcher-chosen coordinator (replacing the Gloo HTTP rendezvous).
- ``--tpu-topology-aware`` orders ranks by ICI torus coordinates (the
  reference orders by hostfile slots).
"""

from __future__ import annotations

import argparse
import dataclasses
import os
import shlex
import socket
import subprocess
import sys
from typing import Dict, List, Optional, Sequence, Tuple

from ..utils.timeline import per_rank_filename


@dataclasses.dataclass
class HostSpec:
    hostname: str
    slots: int


def parse_hosts(hosts: str) -> List[HostSpec]:
    """Parse ``-H host1:2,host2:4`` (reference: runner/common/util/hosts.py)."""
    specs = []
    for part in hosts.split(","):
        part = part.strip()
        if not part:
            continue
        if ":" in part:
            name, slots = part.rsplit(":", 1)
            specs.append(HostSpec(name, int(slots)))
        else:
            specs.append(HostSpec(part, 1))
    return specs


def parse_hostfile(path: str) -> List[HostSpec]:
    """Parse a hostfile with ``hostname slots=N`` lines (reference format)."""
    specs = []
    with open(path) as fh:
        for line in fh:
            line = line.split("#", 1)[0].strip()
            if not line:
                continue
            fields = line.split()
            name = fields[0]
            slots = 1
            for f in fields[1:]:
                if f.startswith("slots="):
                    slots = int(f.split("=", 1)[1])
            specs.append(HostSpec(name, slots))
    return specs


def parse_args(argv: Sequence[str]) -> argparse.Namespace:
    p = argparse.ArgumentParser(
        prog="torovodrun",
        description="Launch a horovod_tpu distributed job",
        usage="torovodrun -np NP [options] <command> [args...]")
    p.add_argument("-np", "--num-proc", type=int, dest="np",
                   help="Total number of worker processes")
    p.add_argument("-H", "--hosts", dest="hosts",
                   help="Comma-separated host:slots list")
    p.add_argument("--hostfile", dest="hostfile",
                   help="Hostfile with 'hostname slots=N' lines")
    p.add_argument("--network-interface", dest="nics",
                   help="Network interface(s) for the control plane")
    p.add_argument("--start-timeout", type=int, default=600)
    p.add_argument("--ssh-port", type=int, default=None)
    p.add_argument("--ssh-identity-file", default=None)
    p.add_argument("--verbose", "-v", action="count", default=0)
    p.add_argument("--config-file", dest="config_file",
                   help="YAML config mirroring the CLI flags")
    p.add_argument("--output-filename", dest="output_filename",
                   help="Redirect worker stdout/stderr to "
                        "<dir>/rank.<N>/stdout|stderr")
    # Tuning knobs forwarded as HOROVOD_* env (reference: launch.py does the
    # same forwarding).
    p.add_argument("--fusion-threshold-mb", type=int, default=None)
    p.add_argument("--cycle-time-ms", type=float, default=None)
    p.add_argument("--cache-capacity", type=int, default=None)
    p.add_argument("--pipeline-chunk-mb", type=float, default=None,
                   help="Chunk size (MB) for pipelined fused reductions; "
                        "0 = one chunk per fused batch (no chunking)")
    p.add_argument("--max-inflight", type=int, default=None,
                   help="Bound on dispatched-but-unsettled fused batches "
                        "(1 = settle inline, no overlap)")
    p.add_argument("--fast-lane-threshold-kb", type=float, default=None,
                   help="Latency fast lane: ungrouped allreduces below "
                        "this many KB skip the fusion buffer (persistent "
                        "pre-compiled single-tensor programs); 0 = off")
    p.add_argument("--partition-threshold-mb", type=float, default=None,
                   help="Split tensors above this many MB into priority-"
                        "inheriting sub-tensors (ByteScheduler-style "
                        "preemption); 0 = off")
    p.add_argument("--spec-ready-after", type=int, default=None,
                   help="Zero-RTT warm path (protocol v7): after a "
                        "response-cache slot has been ready-on-first-"
                        "announce for this many consecutive rounds, the "
                        "coordinator predicts the next-round verdict and "
                        "clients dispatch it without waiting; 0 = off")
    p.add_argument("--round-pipeline", type=int, default=None,
                   help="In-flight negotiation-round window per client: "
                        "1 = lock-step (default), >1 sends round N+1's "
                        "request before round N's response is read")
    p.add_argument("--timeline-filename", default=None)
    p.add_argument("--timeline-mark-cycles", action="store_true")
    p.add_argument("--trace-filename", default=None,
                   help="Arm distributed collective tracing and write one "
                        "trace file per rank at <base>.<rank>; merge with "
                        "`python -m horovod_tpu.trace` (docs/timeline.md)")
    p.add_argument("--trace-ring", type=int, default=None,
                   help="Preallocated trace span-ring capacity "
                        "(default 4096)")
    p.add_argument("--monitor", action="store_true",
                   help="Enable the cross-rank telemetry & health "
                        "subsystem (docs/monitoring.md)")
    p.add_argument("--monitor-port", type=int, default=None,
                   help="Serve /metrics (Prometheus) + /health (JSON) "
                        "over HTTP on rank 0 at this port (implies "
                        "--monitor)")
    p.add_argument("--monitor-interval", type=float, default=None,
                   help="Telemetry snapshot period in seconds (default 5)")
    p.add_argument("--stall-check-time", type=float, default=None)
    p.add_argument("--stall-shutdown-time", type=float, default=None)
    p.add_argument("--round-timeout", type=float, default=None,
                   help="Per-negotiation-round wall-clock deadline in "
                        "seconds (docs/fault_tolerance.md): ranks that "
                        "miss it are declared dead and survivors get a "
                        "typed HVD303 abort; 0/unset disables the "
                        "deadline (dead-socket detection is always on)")
    p.add_argument("--connect-retries", type=int, default=None,
                   help="Bounded controller-connect retries (workers may "
                        "start before the coordinator)")
    p.add_argument("--connect-backoff-ms", type=float, default=None,
                   help="Base backoff between connect retries "
                        "(exponential, jittered)")
    p.add_argument("--autotune", action="store_true")
    p.add_argument("--autotune-log-file", default=None)
    p.add_argument("--sharded", action="store_true",
                   help="ZeRO-sharded optimizer data plane (docs/"
                        "performance.md 'Sharded optimizer (ZeRO)'): "
                        "DistributedOptimizer defaults to sharded=True — "
                        "reduce-scatter of gradients, 1/N-per-rank "
                        "optimizer state, allgather of updates.  "
                        "Forwarded as HOROVOD_SHARDED_OPTIMIZER so every "
                        "rank takes the identical data plane")
    p.add_argument("--sharded-params", action="store_true",
                   help="Full parameter sharding (ZeRO-3/FSDP, docs/"
                        "performance.md 'Full parameter sharding "
                        "(FSDP)'): DistributedOptimizer defaults to "
                        'sharded="full" — parameters live 1/N per rank, '
                        "prefetch allgathers rematerialize them ahead of "
                        "use, gradients reduce-scatter into the owning "
                        "shard.  Forwarded as HOROVOD_SHARDED_PARAMS")
    p.add_argument("--prefetch-depth", type=int, default=None,
                   help="FSDP parameter-gather buckets in flight ahead "
                        "of consumption (HOROVOD_PREFETCH_DEPTH; "
                        "default 2)")
    p.add_argument("--hierarchical-allreduce", action="store_true")
    p.add_argument("--hierarchical-allgather", action="store_true",
                   help="Two-level allgather on the slice topology "
                        "(intra-ICI gather after a cross-DCN leader "
                        "exchange) — the gather legs FSDP makes hot; "
                        "bitwise-identical to flat "
                        "(HOROVOD_HIERARCHICAL_ALLGATHER)")
    p.add_argument("--hierarchical-broadcast", action="store_true",
                   help="Two-level broadcast on the slice topology (one "
                        "cross-DCN leader exchange, then intra-ICI "
                        "fan-out) — the leg serving weight fan-out makes "
                        "hot; bitwise-identical to flat "
                        "(HOROVOD_HIERARCHICAL_BROADCAST)")
    p.add_argument("--serve", action="store_true",
                   help="Serving plane (docs/serving.md): each rank runs "
                        "a continuous-batching front door + replica "
                        "forward loop instead of a training loop.  "
                        "Forwarded as HOROVOD_SERVE; knobs via "
                        "HOROVOD_SERVE_* (port, max batch, buckets, "
                        "deadline, inflight window, queue depth)")
    p.add_argument("--serve-port", type=int, default=None,
                   help="Front-door HTTP port base; rank r listens on "
                        "port+r (HOROVOD_SERVE_PORT; 0/unset = ephemeral)")
    p.add_argument("--hierarchical-controller", action="store_true",
                   help="Two-level control plane (docs/performance.md "
                        "'Control plane at scale'): a per-host agent "
                        "aggregates its ranks' warm-path negotiation "
                        "frames into one fixed-size uplink per round, so "
                        "the rank-0 coordinator's gather scales with "
                        "hosts, not ranks")
    p.add_argument("--tpu-topology-aware", action="store_true", default=True)
    # Elastic (reference: _run_elastic)
    p.add_argument("--min-np", type=int, default=None)
    p.add_argument("--max-np", type=int, default=None)
    p.add_argument("--host-discovery-script", default=None)
    p.add_argument("--tpu-metadata-discovery", action="store_true",
                   help="Discover slice membership + preemption notices "
                        "from the TPU-VM metadata service instead of a "
                        "script (elastic mode; URL override via "
                        "HOROVOD_TPU_METADATA_URL)")
    p.add_argument("--slots-per-host", type=int, default=None)
    p.add_argument("--autoscale", action="store_true",
                   help="Closed-loop autoscaling (elastic mode; docs/"
                        "elastic.md): the driver polls rank 0's monitor "
                        "/health and scales the world itself — out on "
                        "rising load, straggler drain-and-evict on "
                        "monitor attribution, in when idle.  Requires "
                        "--monitor-port; knobs via HOROVOD_AUTOSCALE_*")
    p.add_argument("--autoscale-interval", type=float, default=None,
                   help="Seconds between autoscale policy observations "
                        "(default 5)")
    p.add_argument("--scale-command", default=None,
                   help="Operator capacity hook run on scale decisions "
                        "with HVD_AUTOSCALE_ACTION/TARGET/HOST in env; "
                        "it changes what --host-discovery-script reports "
                        "(e.g. resizes an instance group)")
    p.add_argument("--preempt-grace-s", type=float, default=None,
                   help="Drain grace for preemption notices (elastic "
                        "mode): a noticed host's workers get this long "
                        "to commit + clean-LEAVE before the driver falls "
                        "back to termination (default 30)")
    p.add_argument("--ckpt-dir", default=None,
                   help="Resilient state plane (docs/fault_tolerance.md "
                        "'Resilient state plane'): arm overlap-scheduled "
                        "sharded checkpoints under this directory — each "
                        "rank streams its 1/N state shard through the "
                        "engine's lowest-priority checkpoint lane on "
                        "every elastic-state commit, and re-joining "
                        "ranks restore peer-to-peer from survivors")
    p.add_argument("--ckpt-chunk-mb", type=float, default=None,
                   help="Checkpoint-lane chunk size in MB (one bounded "
                        "write per lane dispatch; default 1)")
    p.add_argument("--ckpt-lane-budget", type=int, default=None,
                   help="Checkpoint chunks dispatched per engine cycle "
                        "tail (default 2)")
    p.add_argument("--commit-max-age-s", type=float, default=None,
                   help="Autoscaler stale-state guard: refuse evict/"
                        "scale_in while the fleet's last state-plane "
                        "commit is older than this (0 = off)")
    # Cluster-scheduler backends (reference P7 ships jsrun/mpirun backends;
    # the TPU equivalents live in runner/tpu_vm.py).
    p.add_argument("--tpu", default=None,
                   help="Launch over a (multi-host) TPU-VM slice: broadcast "
                        "the command to every worker via gcloud tpu-vm ssh")
    p.add_argument("--zone", default=None, help="GCE zone of --tpu")
    p.add_argument("--project", default=None, help="GCP project of --tpu")
    p.add_argument("--gke-jobset", default=None,
                   help="Render a TPU-on-GKE JobSet manifest for this "
                        "command (xpk pattern) instead of launching")
    p.add_argument("--container-image", default=None,
                   help="Container image for --gke-jobset")
    p.add_argument("--gke-num-hosts", type=int, default=None,
                   help="Hosts in the GKE slice (with --gke-jobset)")
    p.add_argument("--gke-accelerator", default=None,
                   help="gke-tpu-accelerator node selector, e.g. "
                        "tpu-v5p-slice / tpu-v5-lite-podslice")
    p.add_argument("--gke-topology", default=None,
                   help="gke-tpu-topology node selector, e.g. 2x2x2 (v4/"
                        "v5p are 3-D) or 4x4 (v5e/v6e)")
    p.add_argument("--gke-chips-per-host", type=int, default=None,
                   help="google.com/tpu resource limit per pod (default 4)")
    p.add_argument("command", nargs=argparse.REMAINDER,
                   help="Training command")
    args = p.parse_args(list(argv))

    if args.config_file:
        _apply_config_file(args)
    if not args.command:
        p.error("no training command given")
    if args.command and args.command[0] == "--":
        args.command = args.command[1:]
    if args.tpu and not args.zone:
        p.error("--tpu requires --zone")
    if (args.tpu or args.gke_jobset) and (args.slots_per_host or 1) > 1:
        # One launched process per host is the TPU-VM/GKE model (the host's
        # local chips are auto-detected by jax); advertising SIZE =
        # hosts*slots while starting one process per host would hang every
        # worker at rendezvous waiting for ranks that never launch.
        p.error("--slots-per-host > 1 is not supported with --tpu/"
                "--gke-jobset: these backends launch ONE process per host "
                "and the process drives all local chips")
    if args.gke_jobset and not (args.container_image and args.gke_num_hosts
                                and args.gke_accelerator
                                and args.gke_topology):
        p.error("--gke-jobset requires --container-image, --gke-num-hosts, "
                "--gke-accelerator and --gke-topology (topologies are "
                "generation-specific; this launcher will not guess)")
    elastic = (args.host_discovery_script is not None
               or args.tpu_metadata_discovery)
    if args.np is None and not elastic and not args.tpu \
            and not args.gke_jobset:
        p.error("-np is required (or elastic --host-discovery-script / "
                "--tpu-metadata-discovery, or a cluster backend "
                "--tpu/--gke-jobset)")
    return args


def _apply_config_file(args: argparse.Namespace):
    """YAML config file mirroring flags (reference: --config-file)."""
    import re

    def parse_scalar(v: str):
        v = v.strip()
        if v.lower() in ("true", "yes"):
            return True
        if v.lower() in ("false", "no"):
            return False
        for cast in (int, float):
            try:
                return cast(v)
            except ValueError:
                pass
        return v

    with open(args.config_file) as fh:
        for line in fh:
            line = line.split("#", 1)[0].strip()
            if not line or ":" not in line:
                continue
            key, val = line.split(":", 1)
            key = key.strip().replace("-", "_")
            if hasattr(args, key) and getattr(args, key) in (None, False):
                setattr(args, key, parse_scalar(val))


def placement(args) -> List[HostSpec]:
    if args.hostfile:
        hosts = parse_hostfile(args.hostfile)
    elif args.hosts:
        hosts = parse_hosts(args.hosts)
    else:
        hosts = [HostSpec("localhost", args.np)]
    total = sum(h.slots for h in hosts)
    if args.np is not None and total < args.np:
        raise ValueError(f"Requested -np {args.np} but hosts provide only "
                         f"{total} slots")
    return hosts


def _free_ports(n: int) -> List[int]:
    from ..common.net import free_ports
    return free_ports(n)


def platform_worker_env(base: Optional[Dict[str, str]] = None) -> Dict[str, str]:
    """Env overrides so user scripts need no platform boilerplate when
    launched on CPU (``JAX_PLATFORMS=cpu`` smoke runs): each worker is ONE
    rank with one CPU device (strip any inherited virtual-device count) and
    cross-process collectives run over gloo.  No-op for TPU workers."""
    base = os.environ if base is None else base
    out: Dict[str, str] = {}
    if base.get("JAX_PLATFORMS", "").startswith("cpu"):
        out["JAX_CPU_COLLECTIVES_IMPLEMENTATION"] = base.get(
            "JAX_CPU_COLLECTIVES_IMPLEMENTATION", "gloo")
        out["XLA_FLAGS"] = " ".join(
            f for f in base.get("XLA_FLAGS", "").split()
            if "xla_force_host_platform_device_count" not in f)
        # TPU site hooks (e.g. the axon sitecustomize) initialize the XLA
        # backend at interpreter start, which forecloses jax.distributed in
        # CPU workers — drop them from the workers' PYTHONPATH.
        if "PYTHONPATH" in base:
            def _is_site_hook(p: str) -> bool:
                # Precise match: only drop entries whose final path component
                # is a TPU site-hook dir, or that actually ship a
                # sitecustomize.py — never unrelated user paths that merely
                # contain the substring (e.g. /home/maxon/lib).
                comp = os.path.basename(os.path.normpath(p))
                if comp in ("axon", ".axon_site"):
                    return True
                return os.path.isfile(os.path.join(p, "sitecustomize.py"))
            out["PYTHONPATH"] = os.pathsep.join(
                p for p in base["PYTHONPATH"].split(os.pathsep)
                if p and not _is_site_hook(p))
    return out


def tuning_env(args) -> Dict[str, str]:
    """HOROVOD_* env derived from the launcher's tuning flags — shared by
    every backend (local/ssh here, TPU-VM/GKE in tpu_vm.py) so a knob can
    never work on one launch path and silently vanish on another."""
    env: Dict[str, str] = {}
    for flag, var, scale in (
            ("fusion_threshold_mb", "HOROVOD_FUSION_THRESHOLD", 1024 * 1024),
            ("cycle_time_ms", "HOROVOD_CYCLE_TIME", 1),
            ("cache_capacity", "HOROVOD_CACHE_CAPACITY", 1),
            ("pipeline_chunk_mb", "HOROVOD_PIPELINE_CHUNK", 1024 * 1024),
            ("max_inflight", "HOROVOD_MAX_INFLIGHT", 1),
            ("fast_lane_threshold_kb", "HOROVOD_FAST_LANE_THRESHOLD", 1024),
            ("partition_threshold_mb", "HOROVOD_PARTITION_THRESHOLD",
             1024 * 1024),
            ("spec_ready_after", "HOROVOD_SPEC_READY_AFTER", 1),
            ("round_pipeline", "HOROVOD_ROUND_PIPELINE", 1),
            ("stall_check_time", "HOROVOD_STALL_CHECK_TIME", 1),
            ("stall_shutdown_time", "HOROVOD_STALL_SHUTDOWN_TIME", 1),
            ("monitor_port", "HOROVOD_MONITOR_PORT", 1),
            ("monitor_interval", "HOROVOD_MONITOR_INTERVAL", 1),
            ("trace_ring", "HOROVOD_TRACE_RING", 1),
            ("round_timeout", "HOROVOD_ROUND_TIMEOUT_S", 1),
            ("connect_retries", "HOROVOD_CONNECT_RETRIES", 1),
            ("connect_backoff_ms", "HOROVOD_CONNECT_BACKOFF_MS", 1),
            ("ckpt_chunk_mb", "HOROVOD_CKPT_CHUNK", 1024 * 1024),
            ("ckpt_lane_budget", "HOROVOD_CKPT_LANE_BUDGET", 1),
            ("commit_max_age_s", "HOROVOD_COMMIT_MAX_AGE_S", 1)):
        val = getattr(args, flag, None)
        if val is not None:
            env[var] = str(int(val * scale) if scale != 1 else val)
    if getattr(args, "ckpt_dir", None):
        env["HOROVOD_CKPT_DIR"] = args.ckpt_dir
    if getattr(args, "monitor", False) \
            or getattr(args, "monitor_port", None):
        env["HOROVOD_MONITOR"] = "1"
    if getattr(args, "timeline_mark_cycles", False):
        env["HOROVOD_TIMELINE_MARK_CYCLES"] = "1"
    if getattr(args, "autotune", False):
        env["HOROVOD_AUTOTUNE"] = "1"
        if getattr(args, "autotune_log_file", None):
            env["HOROVOD_AUTOTUNE_LOG"] = args.autotune_log_file
    if getattr(args, "sharded", False):
        env["HOROVOD_SHARDED_OPTIMIZER"] = "1"
    if getattr(args, "sharded_params", False):
        env["HOROVOD_SHARDED_PARAMS"] = "1"
    if getattr(args, "prefetch_depth", None) is not None:
        env["HOROVOD_PREFETCH_DEPTH"] = str(int(args.prefetch_depth))
    if getattr(args, "hierarchical_allreduce", False):
        env["HOROVOD_HIERARCHICAL_ALLREDUCE"] = "1"
    if getattr(args, "hierarchical_allgather", False):
        env["HOROVOD_HIERARCHICAL_ALLGATHER"] = "1"
    if getattr(args, "hierarchical_broadcast", False):
        env["HOROVOD_HIERARCHICAL_BROADCAST"] = "1"
    if getattr(args, "hierarchical_controller", False):
        env["HOROVOD_HIERARCHICAL_CONTROLLER"] = "1"
    # Serving plane (ISSUE 19, docs/serving.md): the flag plus the knob
    # table travel as env so the workers' Config.from_env() sees them on
    # every launch path; per-rank ports are derived worker-side from the
    # base (rank r listens on serve_port + r when a base is given).
    if getattr(args, "serve", False):
        env["HOROVOD_SERVE"] = "1"
    if getattr(args, "serve_port", None) is not None:
        env["HOROVOD_SERVE_PORT"] = str(int(args.serve_port))
    return env


def wait_and_reap(procs: List[subprocess.Popen],
                  poll_interval_s: float = 0.2) -> int:
    """Wait for every worker, propagate the first failure, terminate
    stragglers (shared by the local/ssh and TPU-VM backends).

    Polls ALL workers rather than waiting in list order: the moment any
    worker exits nonzero, the survivors are terminated — one crashed rank
    must not leave the rest of a slice running until their own timeouts
    fire (the reference launcher's safe_shell_exec kills the process
    group the same way).
    """
    import time
    rc = 0
    live = list(procs)
    try:
        while live:
            still = []
            for p in live:
                code = p.poll()
                if code is None:
                    still.append(p)
                elif code != 0 and rc == 0:
                    rc = code
            live = still
            if rc != 0:
                break
            if live:
                time.sleep(poll_interval_s)
    finally:
        for p in procs:
            if p.poll() is None:
                p.terminate()
        for p in procs:
            if p.poll() is None:
                try:
                    p.wait(timeout=10)
                except subprocess.TimeoutExpired:
                    p.kill()
                    p.wait()
    return rc


def worker_envs(args, hosts: List[HostSpec],
                coordinator: Tuple[str, int, int],
                agent_ports: Optional[List[Optional[int]]] = None
                ) -> List[Dict[str, str]]:
    """Compute the per-rank env injection (reference §3.3: HOROVOD_RANK,
    HOROVOD_SIZE, HOROVOD_LOCAL_RANK, HOROVOD_CROSS_RANK, rendezvous addr).

    ``agent_ports`` (hierarchical control plane): one launcher-allocated
    listen port per host for that host's aggregation agent, injected as
    HOROVOD_AGENT_PORT so every process on a host agrees where its agent
    lives.  A None entry means no injection for that host (remote hosts:
    a port bind-probed on the launcher proves nothing there — the
    config-side deterministic fallback derives one instead)."""
    np_total = args.np
    envs = []
    rank = 0
    for cross_rank, h in enumerate(hosts):
        for local_rank in range(h.slots):
            if rank >= np_total:
                break
            env = platform_worker_env()
            env |= {
                "HOROVOD_RANK": str(rank),
                "HOROVOD_SIZE": str(np_total),
                "HOROVOD_LOCAL_RANK": str(local_rank),
                "HOROVOD_LOCAL_SIZE": str(min(h.slots, np_total - rank + local_rank)),
                "HOROVOD_CROSS_RANK": str(cross_rank),
                "HOROVOD_CROSS_SIZE": str(len(hosts)),
                "HOROVOD_CONTROLLER_ADDR": coordinator[0],
                "HOROVOD_CONTROLLER_PORT": str(coordinator[1]),
                "HOROVOD_CONTROLLER_PORT2": str(coordinator[2]),
                "HOROVOD_HOSTNAME": h.hostname,
            }
            if agent_ports is not None \
                    and agent_ports[cross_rank] is not None:
                env["HOROVOD_AGENT_PORT"] = str(agent_ports[cross_rank])
            env |= tuning_env(args)
            if args.timeline_filename:
                env["HOROVOD_TIMELINE"] = per_rank_filename(
                    args.timeline_filename, rank)
            if getattr(args, "trace_filename", None):
                env["HOROVOD_TRACE"] = per_rank_filename(
                    args.trace_filename, rank)
            envs.append(env)
            rank += 1
    return envs


def ssh_command(host: str, env: Dict[str, str], command: List[str],
                ssh_port: Optional[int] = None,
                identity_file: Optional[str] = None) -> List[str]:
    """Build the remote spawn command (reference: gloo_run's ssh exec via
    safe_shell_exec; tested by asserting on the generated argv, like
    ``test/single/test_run.py``)."""
    exports = " ".join(f"{k}={shlex.quote(v)}" for k, v in sorted(env.items()))
    remote = f"cd {shlex.quote(os.getcwd())} && env {exports} " + \
        " ".join(shlex.quote(c) for c in command)
    cmd = ["ssh", "-o", "StrictHostKeyChecking=no"]
    if ssh_port:
        cmd += ["-p", str(ssh_port)]
    if identity_file:
        cmd += ["-i", identity_file]
    cmd += [host, remote]
    return cmd


def launch_workers(args, hosts: List[HostSpec],
                   addrs: Optional[Dict[str, str]] = None) -> int:
    """Spawn all workers, wait, propagate first failure (local + ssh).

    ``addrs`` (from the bootstrap probe phase) overrides the coordinator
    address with host 0's resolved control-plane address — this is what
    makes ``--network-interface`` actually select the control plane."""
    from ..common.net import is_local_host
    # Hierarchical control plane: one extra port per host for its
    # aggregation agent.  Bind-probed HERE only for local/loopback hosts
    # (the CPU test meshes) — a port free on the launcher proves nothing
    # on a remote host, so remote hosts get NO injection and derive their
    # own via the HOROVOD_AGENT_PORT=0 fallback in common/config.py.
    hier = getattr(args, "hierarchical_controller", False)
    agent_ports = None
    if hier:
        local_hosts = [is_local_host(h.hostname) for h in hosts]
        probed = iter(_free_ports(2 + sum(local_hosts)))
        ports = [next(probed), next(probed)]
        agent_ports = [next(probed) if loc else None for loc in local_hosts]
    else:
        ports = _free_ports(2)
    if addrs:
        coord_host = addrs[hosts[0].hostname]
    else:
        coord_host = (hosts[0].hostname if hosts[0].hostname != "localhost"
                      else "127.0.0.1")
    coord = (coord_host, ports[0], ports[1])
    envs = worker_envs(args, hosts, coord, agent_ports=agent_ports)
    procs: List[subprocess.Popen] = []
    for rank, env in enumerate(envs):
        host = env["HOROVOD_HOSTNAME"]
        full_env = {**os.environ, **env}
        stdout = stderr = None
        if args.output_filename:
            d = os.path.join(args.output_filename, f"rank.{rank}")
            os.makedirs(d, exist_ok=True)
            stdout = open(os.path.join(d, "stdout"), "w")
            stderr = open(os.path.join(d, "stderr"), "w")
        if host in ("localhost", "127.0.0.1", socket.gethostname()):
            proc = subprocess.Popen(args.command, env=full_env,
                                    stdout=stdout, stderr=stderr)
        else:
            cmd = ssh_command(host, env, args.command, args.ssh_port,
                              args.ssh_identity_file)
            proc = subprocess.Popen(cmd, env=os.environ.copy(),
                                    stdout=stdout, stderr=stderr)
        procs.append(proc)
    return wait_and_reap(procs)


def main(argv: Sequence[str]) -> int:
    args = parse_args(argv)
    if args.gke_jobset:
        from .tpu_vm import render_gke_jobset
        sys.stdout.write(render_gke_jobset(args, args.gke_num_hosts))
        return 0
    if args.tpu:
        from .tpu_vm import run_tpu_vm
        return run_tpu_vm(args)
    if (args.host_discovery_script is not None
            or getattr(args, "tpu_metadata_discovery", False)):
        from ..elastic.driver import run_elastic
        return run_elastic(args)
    hosts = placement(args)
    if args.verbose:
        print(f"[torovodrun] launching np={args.np} over "
              f"{[(h.hostname, h.slots) for h in hosts]}", file=sys.stderr)
    # Pre-launch bootstrap (reference P8): probe NICs + mutual connectivity
    # whenever a host is remote or an explicit interface was requested —
    # refuse fast with the exact broken pair instead of spawning workers
    # that would hang in rendezvous.
    addrs = None
    from ..common.net import is_local_host
    if args.nics or any(not is_local_host(h.hostname) for h in hosts):
        from .bootstrap import bootstrap_hosts
        try:
            addrs = bootstrap_hosts(
                hosts, nic=args.nics, ssh_port=args.ssh_port,
                identity_file=args.ssh_identity_file,
                timeout_s=min(args.start_timeout, 120),
                verbose=args.verbose)
        except RuntimeError as exc:
            print(f"[torovodrun] {exc}", file=sys.stderr)
            return 1
    return launch_workers(args, hosts, addrs)
