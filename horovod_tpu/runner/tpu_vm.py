"""Cluster-scheduler launch backends: multi-host TPU-VM and GKE JobSet.

Reference parity: the reference launcher ships cluster backends beyond
plain ssh — LSF ``jsrun`` (``horovod/runner/js_run.py``) and mpirun
(``horovod/runner/mpi_run.py``), selected from ``horovodrun`` flags
(SURVEY.md §2b P7).  The TPU-native equivalents are:

- **TPU-VM backend** (``torovodrun --tpu NAME --zone Z ...``): resolves the
  pod slice's workers from ``gcloud compute tpus tpu-vm describe`` and
  broadcasts one per-worker ssh command via
  ``gcloud compute tpus tpu-vm ssh --worker=N``, with the full
  ``HOROVOD_*`` env injected (rank = worker index, coordinator = worker
  0's internal IP).  This is how multi-host TPU pod slices are actually
  driven — every worker runs the same command, differing only in env.
- **GKE backend** (``torovodrun --gke-jobset NAME --container-image IMG``):
  renders a JobSet manifest (the xpk-style TPU-on-GKE pattern): one
  replicated Job spanning the slice's hosts, rank derived from the
  completion index, rendezvous via the headless service's index-0 DNS
  name.  Rendered to stdout/file — applying it is ``kubectl``'s job, and
  keeping this a pure generator is what makes it hermetically testable
  (the reference tests its mpirun/jsrun backends the same way: assert on
  the generated command line, ``test/single/test_run.py``).

Both backends are pure functions from (args, cluster description) to
commands/manifests, with the subprocess runner injectable for tests.
"""

from __future__ import annotations

import dataclasses
import json
import os
import shlex
import subprocess
from typing import Callable, Dict, List, Optional, Sequence


@dataclasses.dataclass
class TPUEndpoint:
    """One worker VM of a (possibly multi-host) TPU slice."""
    worker_id: int
    internal_ip: str


def describe_tpu(name: str, zone: str, project: Optional[str] = None,
                 runner: Callable = subprocess.run) -> List[TPUEndpoint]:
    """Resolve a TPU's worker endpoints via ``gcloud ... describe``.

    ``runner`` is injectable (tests pass a fake returning canned JSON).
    """
    cmd = ["gcloud", "compute", "tpus", "tpu-vm", "describe", name,
           "--zone", zone, "--format", "json"]
    if project:
        cmd += ["--project", project]
    res = runner(cmd, capture_output=True, text=True, check=True)
    info = json.loads(res.stdout)
    state = info.get("state", "UNKNOWN")
    if state != "READY":
        raise RuntimeError(
            f"TPU {name!r} is {state}, not READY — wait for it (or recreate "
            f"it) before launching")
    eps = []
    for i, ep in enumerate(info.get("networkEndpoints", [])):
        ip = ep.get("ipAddress", "")
        if not ip:
            raise RuntimeError(
                f"TPU {name!r} worker {i} has no ipAddress yet — the slice "
                f"is not fully provisioned")
        eps.append(TPUEndpoint(worker_id=i, internal_ip=ip))
    if not eps:
        raise RuntimeError(f"TPU {name!r} reports no networkEndpoints")
    return eps


def _coordinator_env(coord_ip: str, ports: Sequence[int]) -> Dict[str, str]:
    return {
        "HOROVOD_CONTROLLER_ADDR": coord_ip,
        "HOROVOD_CONTROLLER_PORT": str(ports[0]),
        "HOROVOD_CONTROLLER_PORT2": str(ports[1]),
    }


def tpu_vm_worker_env(args, endpoints: Sequence[TPUEndpoint],
                      worker_id: int,
                      ports: Sequence[int]) -> Dict[str, str]:
    """The HOROVOD_* env for one slice worker.

    One launched process per host (rank = cross_rank = worker index): on a
    TPU slice the worker index IS the ICI-topology order the runtime
    expects, and the process drives all of the host's local chips
    (jax auto-detects them — no per-slot process fan-out, which is why
    ``--slots-per-host`` is rejected for this backend at parse time).
    """
    from .run import tuning_env
    n_hosts = len(endpoints)
    env = _coordinator_env(endpoints[0].internal_ip, ports)
    env |= {
        # Process-world values (control plane: TCP controller rank/world).
        # HOROVOD_ONE_PROC_PER_HOST makes the device-world accessors
        # (rank/local_rank/local_size) topology-derived instead — on a
        # multi-chip host rank() must be the first local chip's global
        # rank, not the host index.
        "HOROVOD_RANK": str(worker_id),
        "HOROVOD_SIZE": str(n_hosts),
        "HOROVOD_LOCAL_RANK": "0",
        "HOROVOD_LOCAL_SIZE": "1",
        "HOROVOD_CROSS_RANK": str(worker_id),
        "HOROVOD_CROSS_SIZE": str(n_hosts),
        "HOROVOD_HOSTNAME": f"worker-{worker_id}",
        "HOROVOD_ONE_PROC_PER_HOST": "1",
    }
    env |= tuning_env(args)   # same knob forwarding as every other backend
    # Per-rank output files share ONE suffix scheme across every backend
    # (utils.timeline.per_rank_filename); worker_id is this process's
    # global rank in one-proc-per-host mode.
    from ..utils.timeline import per_rank_filename
    if getattr(args, "timeline_filename", None):
        env["HOROVOD_TIMELINE"] = per_rank_filename(
            args.timeline_filename, worker_id)
    if getattr(args, "trace_filename", None):
        env["HOROVOD_TRACE"] = per_rank_filename(
            args.trace_filename, worker_id)
    return env


def tpu_vm_ssh_commands(args, endpoints: Sequence[TPUEndpoint],
                        ports: Sequence[int]) -> List[List[str]]:
    """One ``gcloud compute tpus tpu-vm ssh --worker=N`` argv per worker."""
    cmds = []
    inner = " ".join(shlex.quote(c) for c in args.command)
    # Same cwd convention as the plain ssh backend (ssh_command): the
    # launcher's working directory is assumed synced at the same path on
    # every worker (the standard TPU-VM NFS/rsync workflow).
    cwd = shlex.quote(os.getcwd())
    for ep in endpoints:
        env = tpu_vm_worker_env(args, endpoints, ep.worker_id, ports)
        exports = " ".join(f"{k}={shlex.quote(v)}"
                           for k, v in sorted(env.items()))
        remote = f"cd {cwd} && env {exports} {inner}"
        cmd = ["gcloud", "compute", "tpus", "tpu-vm", "ssh", args.tpu,
               "--zone", args.zone, "--worker", str(ep.worker_id),
               "--command", remote]
        if getattr(args, "project", None):
            cmd += ["--project", args.project]
        cmds.append(cmd)
    return cmds


def run_tpu_vm(args, runner: Callable = subprocess.run,
               popen: Callable = subprocess.Popen) -> int:
    """Describe the slice, broadcast the command, propagate first failure."""
    from .run import wait_and_reap
    endpoints = describe_tpu(args.tpu, args.zone,
                             getattr(args, "project", None), runner=runner)
    ports = (29400, 29401)  # fixed: every worker must agree without a probe
    procs = [popen(cmd) for cmd in tpu_vm_ssh_commands(args, endpoints,
                                                       ports)]
    return wait_and_reap(procs)


# ------------------------------------------------------------------ GKE
_JOBSET_TEMPLATE = """\
apiVersion: jobset.x-k8s.io/v1alpha2
kind: JobSet
metadata:
  name: {name}
spec:
  replicatedJobs:
  - name: workers
    replicas: 1
    template:
      spec:
        parallelism: {n_hosts}
        completions: {n_hosts}
        completionMode: Indexed
        template:
          spec:
            restartPolicy: Never
            nodeSelector:
              cloud.google.com/gke-tpu-accelerator: {accelerator}
              cloud.google.com/gke-tpu-topology: {topology}
            containers:
            - name: worker
              image: {image}
              ports:
              - containerPort: 29400
              - containerPort: 29401
              securityContext:
                privileged: true
              command: ["/bin/sh", "-c"]
              args:
              - >-
                HOROVOD_CROSS_RANK=$JOB_COMPLETION_INDEX
                HOROVOD_RANK=$JOB_COMPLETION_INDEX
                HOROVOD_SIZE={n_hosts}
                HOROVOD_LOCAL_RANK=0
                HOROVOD_LOCAL_SIZE=1
                HOROVOD_CROSS_SIZE={n_hosts}
                HOROVOD_ONE_PROC_PER_HOST=1
                HOROVOD_CONTROLLER_ADDR={name}-workers-0-0.{name}
                HOROVOD_CONTROLLER_PORT=29400
                HOROVOD_CONTROLLER_PORT2=29401
                {command}
              resources:
                limits:
                  google.com/tpu: {chips_per_host}
"""


def render_gke_jobset(args, n_hosts: int) -> str:
    """Render the JobSet manifest for a TPU-on-GKE launch (xpk pattern).

    Rank layout: the Job's completion index is the host/cross rank;
    rendezvous rides JobSet's per-index headless DNS
    (``<jobset>-workers-0-0.<jobset>`` = worker 0).  The manifest is a
    string so tests assert on it and operators pipe it to ``kubectl apply
    -f -`` (this launcher deliberately does not wrap kubectl).

    Accelerator/topology node selectors come from ``--gke-accelerator`` /
    ``--gke-topology`` — they are REQUIRED knowledge the user has and this
    code cannot infer (topologies are generation-specific, e.g. 3-D on
    v4/v5p, 2-D on v5e/v6e).

    One pod per host, rank = completion index (same one-process-per-host
    model as the TPU-VM backend; the pod drives all its local chips).
    """
    from .run import tuning_env
    extra_env = " ".join(
        f"{k}={v}" for k, v in sorted(tuning_env(args).items()))
    return _JOBSET_TEMPLATE.format(
        name=args.gke_jobset,
        n_hosts=n_hosts,
        image=args.container_image,
        command=((extra_env + " ") if extra_env else "")
        + " ".join(shlex.quote(c) for c in args.command),
        accelerator=args.gke_accelerator,
        topology=args.gke_topology,
        chips_per_host=getattr(args, "gke_chips_per_host", None) or 4,
    )
