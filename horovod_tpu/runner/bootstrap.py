"""Pre-launch host bootstrap: NIC discovery + mutual connectivity matrix.

Parity with the reference's driver/task bootstrap services
(``horovod/runner/driver/driver_service.py``,
``horovod/runner/common/service/task_service.py``, ``horovod/runner/task/``
— SURVEY.md §2b P8, §3.3): before spawning workers, the launcher starts a
TCP **driver service**, launches a small **probe task** on every host, and

1. each probe enumerates its NICs/addresses and registers back;
2. the driver picks each host's control-plane address — the
   ``--network-interface`` NIC's address when given (refusing fast if a
   host lacks it), else the address the probe's registration arrived from
   (the interface that actually routes to the launcher);
3. every probe is told every other probe's (address, port) and must
   TCP-connect to each; the driver assembles the mutual connectivity
   matrix and refuses the launch naming the exact broken host pair.

The probes are dependency-light (no jax/tf import) so they start in
milliseconds over ssh.  Wire protocol: one JSON object per line.
"""

from __future__ import annotations

import json
import os
import socket
import struct
import subprocess
import sys
import threading
import time
from typing import Dict, List, Optional, Tuple

from ..utils.logging import get_logger

log = get_logger()

_CONNECT_TIMEOUT_S = 5.0


def list_nics() -> Dict[str, str]:
    """interface name → IPv4 address for every configured interface.

    Uses SIOCGIFADDR ioctls (pure stdlib — the reference shells out to
    psutil; this image has no psutil).  Interfaces without an IPv4 address
    are skipped.
    """
    import fcntl

    nics: Dict[str, str] = {}
    s = socket.socket(socket.AF_INET, socket.SOCK_DGRAM)
    try:
        for _idx, name in socket.if_nameindex():
            try:
                packed = fcntl.ioctl(
                    s.fileno(), 0x8915,  # SIOCGIFADDR
                    struct.pack("256s", name[:15].encode()))
                nics[name] = socket.inet_ntoa(packed[20:24])
            except OSError:
                continue
    finally:
        s.close()
    return nics


def _read_json_line(fh) -> Optional[dict]:
    line = fh.readline()
    if not line:
        return None
    return json.loads(line)


def _send_json(sock: socket.socket, obj: dict):
    sock.sendall((json.dumps(obj) + "\n").encode())


# --------------------------------------------------------------- probe task
def probe_main(driver_addr: str, driver_port: int, label: str,
               nic: Optional[str] = None) -> int:
    """Runs on each host (``python -m horovod_tpu.runner.task_probe``)."""
    nics = list_nics()
    chosen = None
    if nic:
        for want in nic.split(","):
            if want in nics:
                chosen = nics[want]
                break

    # Reachability listener: peers prove connectivity by connecting here.
    lsock = socket.socket()
    lsock.bind(("", 0))
    lsock.listen(16)
    lport = lsock.getsockname()[1]
    stop = threading.Event()

    def acceptor():
        lsock.settimeout(0.5)
        while not stop.is_set():
            try:
                conn, _ = lsock.accept()
                conn.close()
            except socket.timeout:
                continue
            except OSError:
                return

    t = threading.Thread(target=acceptor, daemon=True)
    t.start()

    try:
        s = socket.create_connection((driver_addr, driver_port),
                                     timeout=_CONNECT_TIMEOUT_S)
    except OSError as exc:
        print(f"probe {label}: cannot reach driver at "
              f"{driver_addr}:{driver_port}: {exc}", file=sys.stderr)
        return 1
    try:
        s.settimeout(60.0)
        _send_json(s, {"type": "register", "host": label, "nics": nics,
                       "addr": chosen, "listen_port": lport,
                       "slots": os.cpu_count() or 1,
                       "nic_requested": nic or "",
                       "nic_found": chosen is not None or not nic})
        fh = s.makefile()
        msg = _read_json_line(fh)
        if msg is None or msg.get("type") != "check":
            return 0 if msg is None else 1   # driver aborted early
        reachable = {}
        for peer in msg["peers"]:
            if peer["host"] == label:
                continue
            try:
                c = socket.create_connection(
                    (peer["addr"], peer["port"]), timeout=_CONNECT_TIMEOUT_S)
                c.close()
                reachable[peer["host"]] = True
            except OSError:
                reachable[peer["host"]] = False
        _send_json(s, {"type": "result", "host": label,
                       "reachable": reachable})
        _read_json_line(fh)   # wait for the driver's close/ack
        return 0
    finally:
        stop.set()
        lsock.close()
        s.close()


# ------------------------------------------------------------ driver service
class DriverService:
    """Launcher-side bootstrap service: collects probe registrations,
    assigns control-plane addresses, and validates the connectivity
    matrix."""

    def __init__(self, expected_hosts: List[str], nic: Optional[str] = None,
                 timeout_s: float = 60.0):
        self.expected = list(expected_hosts)
        self.nic = nic
        self.timeout_s = timeout_s
        self._sock = socket.socket()
        self._sock.bind(("", 0))
        self._sock.listen(len(self.expected) + 4)

    @property
    def port(self) -> int:
        return self._sock.getsockname()[1]

    def close(self):
        self._sock.close()

    def run(self) -> Dict[str, str]:
        """Returns host → control-plane address; raises RuntimeError with
        the exact missing host / missing NIC / broken pair otherwise."""
        deadline = time.monotonic() + self.timeout_s
        # host -> (socket, file-reader, register msg, observed peer addr).
        # ONE makefile() per connection: a second reader would miss bytes
        # the first one buffered past the register line.
        registered: Dict[str, tuple] = {}
        try:
            while len(registered) < len(self.expected):
                remaining = deadline - time.monotonic()
                if remaining <= 0:
                    missing = sorted(set(self.expected) - set(registered))
                    raise RuntimeError(
                        f"host bootstrap timed out: no probe registration "
                        f"from host(s) {missing} within {self.timeout_s}s — "
                        f"check ssh access and that the hosts can reach the "
                        f"launcher")
                self._sock.settimeout(remaining)
                try:
                    conn, peer = self._sock.accept()
                except socket.timeout:
                    continue
                # The probe's connectivity phase legitimately takes up to
                # one connect timeout per unreachable peer before it can
                # answer — scale the read timeout accordingly so a cluster
                # with many broken pairs still gets the exact broken-pair
                # diagnostic instead of a spurious "probe wedged".
                conn.settimeout(
                    30.0 + len(self.expected) * _CONNECT_TIMEOUT_S)
                fh = conn.makefile()
                try:
                    msg = _read_json_line(fh)
                except (OSError, ValueError):
                    conn.close()
                    continue          # garbled/stalled registration attempt
                if not msg or msg.get("type") != "register":
                    conn.close()
                    continue
                host = msg["host"]
                if host not in self.expected or host in registered:
                    conn.close()
                    continue
                registered[host] = (conn, fh, msg, peer[0])

            # Control-plane address per host.
            addrs: Dict[str, str] = {}
            for host, (conn, fh, msg, peer_addr) in registered.items():
                if self.nic:
                    if not msg.get("nic_found"):
                        raise RuntimeError(
                            f"host {host!r} has no interface named "
                            f"{self.nic!r} (available: "
                            f"{sorted(msg.get('nics', {}))}); fix "
                            f"--network-interface")
                    addrs[host] = msg["addr"]
                else:
                    # The address the registration actually arrived from:
                    # the interface that routes host → launcher.  Loopback
                    # means a local probe — keep it local.
                    addrs[host] = peer_addr

            # Mutual connectivity matrix.
            peers = [{"host": h, "addr": addrs[h],
                      "port": registered[h][2]["listen_port"]}
                     for h in self.expected]
            for host, (conn, _fh, _msg, _p) in registered.items():
                _send_json(conn, {"type": "check", "peers": peers})
            results: Dict[str, dict] = {}
            for host, (conn, fh, _msg, _p) in registered.items():
                try:
                    res = _read_json_line(fh)
                except (OSError, ValueError) as exc:
                    # Wedged probe / garbled line: keep the promised clean
                    # diagnostic naming the host (not a raw traceback).
                    raise RuntimeError(
                        f"host bootstrap: probe on {host!r} wedged or sent "
                        f"garbage during the connectivity check "
                        f"({exc})") from exc
                if not res or res.get("type") != "result":
                    raise RuntimeError(
                        f"host bootstrap: probe on {host!r} died during the "
                        f"connectivity check")
                results[host] = res["reachable"]
            for a in self.expected:
                for b in self.expected:
                    if a == b:
                        continue
                    if not results[a].get(b, False):
                        raise RuntimeError(
                            f"connectivity check failed: host {a!r} cannot "
                            f"reach host {b!r} at {addrs[b]}:"
                            f"{registered[b][2]['listen_port']} — fix the "
                            f"network (or --network-interface) before "
                            f"launching")
            for host, (conn, _fh, _msg, _p) in registered.items():
                try:
                    _send_json(conn, {"type": "done"})
                except OSError:
                    pass
            return addrs
        finally:
            for conn, _fh, _msg, _p in registered.values():
                conn.close()


def bootstrap_hosts(hosts, nic: Optional[str] = None,
                    ssh_port: Optional[int] = None,
                    identity_file: Optional[str] = None,
                    timeout_s: float = 60.0,
                    verbose: int = 0) -> Dict[str, str]:
    """Probe every host and return host → control-plane address.

    Raises RuntimeError naming the exact failure (unreachable host, missing
    NIC, or broken host pair).
    """
    from ..common.net import is_local_host, routable_addr
    from .run import ssh_command

    labels = [h.hostname for h in hosts]
    svc = DriverService(labels, nic=nic, timeout_s=timeout_s)
    procs: List[subprocess.Popen] = []
    try:
        any_remote = any(not is_local_host(h) for h in labels)
        driver_addr = routable_addr() if any_remote else "127.0.0.1"
        for label in labels:
            cmd = [sys.executable, "-m", "horovod_tpu.runner.task_probe",
                   "--driver-addr", driver_addr,
                   "--driver-port", str(svc.port),
                   "--label", label]
            if nic:
                cmd += ["--nic", nic]
            if is_local_host(label):
                procs.append(subprocess.Popen(cmd))
            else:
                remote_cmd = ["python3", "-m",
                              "horovod_tpu.runner.task_probe",
                              "--driver-addr", driver_addr,
                              "--driver-port", str(svc.port),
                              "--label", label] + (
                                  ["--nic", nic] if nic else [])
                procs.append(subprocess.Popen(
                    ssh_command(label, {}, remote_cmd, ssh_port,
                                identity_file)))
        addrs = svc.run()
        if verbose:
            log.warning("bootstrap: control-plane addresses %s", addrs)
        return addrs
    finally:
        svc.close()
        for p in procs:
            try:
                p.wait(timeout=10)
            except subprocess.TimeoutExpired:
                p.terminate()
