"""Process sets: named subgroups of ranks with their own sub-mesh.

TPU-native equivalent of the reference's process sets
(``horovod/common/process_set.cc``, ``horovod/common/process_sets.py`` —
SURVEY.md §2a N12): where the reference gives each set its own MPI/NCCL
sub-communicator + controller + tensor queue, we give each set its own
``jax.sharding.Mesh`` over the subset of devices; eager collectives compile
against that sub-mesh, and the coordinator keys negotiation by process-set id.
"""

from __future__ import annotations

import dataclasses
from typing import Dict, List, Optional, Sequence

import numpy as np
from jax.sharding import Mesh


class ProcessSet:
    """A named subgroup of ranks over which collectives can run.

    ``ProcessSet([0, 1])`` mirrors ``hvd.ProcessSet([0, 1])`` in the
    reference.  The special ``global_process_set`` contains every rank.
    """

    def __init__(self, ranks: Optional[Sequence[int]] = None):
        self.ranks: Optional[List[int]] = sorted(ranks) if ranks is not None else None
        self.process_set_id: Optional[int] = None
        self._mesh: Optional[Mesh] = None
        self._axis_name: Optional[str] = None

    def _materialize(self, ps_id: int, devices, axis_name: str):
        self.process_set_id = ps_id
        self._axis_name = axis_name
        if self.ranks is None:
            self.ranks = list(range(len(devices)))
        bad = [r for r in self.ranks if r < 0 or r >= len(devices)]
        if bad:
            raise ValueError(f"ProcessSet ranks out of range: {bad}")
        sub = np.array([devices[r] for r in self.ranks], dtype=object)
        self._mesh = Mesh(sub, (axis_name,))

    @property
    def mesh(self) -> Mesh:
        if self._mesh is None:
            raise RuntimeError("ProcessSet not yet registered; call add_process_set() "
                               "or pass it to init()")
        return self._mesh

    @property
    def axis_name(self) -> str:
        assert self._axis_name is not None
        return self._axis_name

    def size(self) -> int:
        if self.ranks is None:
            raise RuntimeError("ProcessSet not yet registered")
        return len(self.ranks)

    def rank_in_set(self, global_rank: int) -> int:
        """Position of a global rank inside this set (ValueError if absent)."""
        assert self.ranks is not None
        return self.ranks.index(global_rank)

    def included(self, global_rank: int) -> bool:
        assert self.ranks is not None
        return global_rank in self.ranks

    def __repr__(self):
        return f"ProcessSet(id={self.process_set_id}, ranks={self.ranks})"


class ProcessSetTable:
    """Registry of process sets, id 0 = global set."""

    def __init__(self):
        self._sets: Dict[int, ProcessSet] = {}
        self._next_id = 0

    def initialize(self, devices, axis_name: str,
                   extra_sets: Optional[Sequence[ProcessSet]] = None) -> ProcessSet:
        self._sets.clear()
        self._next_id = 0
        global_set = ProcessSet(None)
        self.add(global_set, devices, axis_name)
        for ps in (extra_sets or []):
            self.add(ps, devices, axis_name)
        return global_set

    def add(self, ps: ProcessSet, devices, axis_name: str) -> ProcessSet:
        for existing in self._sets.values():
            if existing.ranks == (sorted(ps.ranks) if ps.ranks is not None
                                  else list(range(len(devices)))):
                raise ValueError(f"A process set with ranks {existing.ranks} already exists")
        ps._materialize(self._next_id, devices, axis_name)
        self._sets[self._next_id] = ps
        self._next_id += 1
        return ps

    def remove(self, ps: ProcessSet):
        if ps.process_set_id == 0:
            raise ValueError("Cannot remove the global process set")
        if ps.process_set_id in self._sets:
            del self._sets[ps.process_set_id]
        ps.process_set_id = None
        ps._mesh = None

    def get(self, ps_id: int) -> ProcessSet:
        return self._sets[ps_id]

    @property
    def global_set(self) -> ProcessSet:
        return self._sets[0]

    def all_sets(self) -> List[ProcessSet]:
        return list(self._sets.values())


# Singleton placeholder mirroring hvd.global_process_set; bound at init().
global_process_set = ProcessSet(None)
