"""TCP controller client: multi-process negotiation for the engine.

The Python face of ``csrc/coordinator.cc`` — plays the role of the
reference's ``Controller::ComputeResponseList`` transport half (SURVEY.md
§3.2 step 2): every coordinator cycle, announce newly-pending tensor names,
receive the globally-ready ordered name list, and hand ready entries back to
the engine (which batches and executes them identically on every process).

Rank 0 additionally hosts the server thread (native, lock-step rounds).
"""

from __future__ import annotations

import ctypes
import dataclasses
import itertools
import struct
import threading
from typing import Dict, List, Optional, Sequence, Tuple

from . import native
from .exceptions import (
    HorovodInternalError, JoinTimeoutError, PeerFailureError,
    RoundTimeoutError,
)
from .net import retry_with_backoff
from ..testing import faults as _faults
from ..utils.logging import get_logger

log = get_logger()

_RESP_CAP = 4 * 1024 * 1024

# Monitor side-channel section marker ("MON1" little-endian) — protocol v3.
# Matches kMonMagic in csrc/coordinator.cc.
_MON_MAGIC = 0x314E4F4D
# Fault-tolerance capability section marker ("FLT1") — protocol v4; rides
# the first request/response only (warm rounds carry zero extra bytes).
_FLT_MAGIC = 0x31544C46
# Hierarchical control plane capability marker ("AGG5") — protocol v5;
# round 1 only in both directions, exactly the FLT1 pattern.  On the
# request side it rides BEFORE FLT1: the server's pre-processing FLT1
# salvage reads the round-1 frame's final 8 bytes, so FLT1 stays last.
_AGG_MAGIC = 0x35474741
# Typed abort frame: escape word + magic ("ABT4").  Matches kAbortEscape /
# kAbortMagic in csrc/coordinator.cc.
_ABORT_ESCAPE = 0xFFFFFFFF
_ABORT_MAGIC = 0x34544241
# Clean-LEAVE (protocol v6): request-side escape word (an impossible
# n_announce) + "LVE6" magic, which doubles as the round-1 capability ad in
# both directions and as the response-side leave-notice section marker.
# Matches kLeaveEscape / kLeaveMagic in csrc/coordinator.cc.
_LEAVE_ESCAPE = 0xFFFFFFFE
_LVE_MAGIC = 0x3645564C
# Zero-RTT warm path (protocol v7): "ZRT7" is the round-1 capability ad in
# both directions, the response-side next-round prediction section, and
# the request-side one-byte speculation confirm.  Matches kZrtMagic in
# csrc/coordinator.cc.
_ZRT_MAGIC = 0x3754525A


@dataclasses.dataclass
class ResponseCacheStats:
    """Client-side response-cache telemetry (timeline/bench/tests).

    ``hits``/``misses`` count per-tensor announces by wire form (bitvector
    vs full metadata); ``invalidations`` counts slots dropped for any
    reason — server-coordinated evictions, ``forget()``, or local capacity
    trims; ``full_announces``/``bit_announces`` are the cumulative frame
    contents the tier-1 regression guard asserts on."""
    hits: int = 0
    misses: int = 0
    invalidations: int = 0       # slots this client actually dropped
    evictions: int = 0           # server eviction broadcasts seen (counted
                                 # even when a local trim got there first)
    full_announces: int = 0
    bit_announces: int = 0

    def hit_rate(self) -> Optional[float]:
        total = self.hits + self.misses
        return (self.hits / total) if total else None


class NegotiationError(RuntimeError):
    """A collective was submitted inconsistently across ranks (shape/dtype/
    op divergence).  Per-tensor: raised from ``synchronize()`` of the
    offending collective only; the runtime stays alive (reference: the
    controller's per-tensor error Responses, SURVEY.md N2/§5).

    Deliberately NOT a HorovodInternalError — an elastic wrapper must not
    respond to an application bug by resetting the world."""


class TCPController:
    """Engine-facing controller (engine calls ``negotiate`` each cycle)."""

    def __init__(self, addr: str, port: int, rank: int, world: int,
                 stall_warn_s: float = 60.0, connect_timeout_ms: int = 60000,
                 cache_capacity: int = 2048, round_timeout_s: float = 0.0,
                 connect_retries: int = 3,
                 connect_backoff_ms: float = 500.0,
                 server_port: Optional[int] = None,
                 spec_ready_after: int = 0,
                 round_pipeline: int = 1,
                 zero_rtt: bool = True,
                 spec_seed: int = 0,
                 spec_streak_hint: int = 0):
        # server_port: where rank 0 binds the root coordinator when that
        # differs from where this client connects — the hierarchical
        # control plane (protocol v5) points every client at its local
        # HostAgent while the root server keeps the launcher-advertised
        # port.  None (default, flat mode) = same port for both.
        self._lib = native.load()
        self.rank = rank
        self.world = world
        self._server = None
        # Control-plane fault tolerance (protocol v4, HOROVOD_ROUND_
        # TIMEOUT_S): the server declares a rank dead when its socket dies
        # or it misses the per-round deadline, and broadcasts a typed
        # ABORT; this client additionally bounds its own response wait at
        # 2x the deadline (the server's verdict — armed at the round's
        # first frame, i.e. no later than our own send — must win the race
        # so failures carry dead-rank attribution; the client timeout is
        # the backstop for a wedged coordinator).  0 disables both
        # deadlines; dead-socket detection is always on.
        self.round_timeout_s = max(0.0, float(round_timeout_s))
        # Monitor-installed attribution hook: called with the dead-rank
        # list (or None for unattributed timeouts) to enrich HVD303 errors
        # with snapshot ages / ledger tails.  Telemetry only — guarded.
        self.fault_enricher = None
        # Latches once the server advertises protocol v4 (FLT1 section in
        # round 1's response) — the fault-frame analogue of
        # peer_monitor_proto below.
        self.peer_fault_proto = False
        # Latches once the server advertises protocol v5 (AGG5 section in
        # round 1's response): the coordinator understands per-host agent
        # connections, so a HostAgent between this client and the root is
        # known-compatible.  Purely observational on the rank client — its
        # own wire bytes are IDENTICAL either way (the frame guard pins
        # this), which is what lets the agent forward them verbatim.
        self.peer_hier_proto = False
        # Latches once the server advertises protocol v6 (LVE6 section):
        # this client may announce its own clean departure with a typed
        # LEAVE frame instead of a blind socket sever — see leave().
        self.peer_leave_proto = False
        # Zero-RTT warm path (protocol v7, docs/performance.md "Zero-RTT
        # warm path").  spec_ready_after mirrors the server knob (rank 0
        # starts the server with it); on the CLIENT it gates consuming
        # predictions — 0 keeps every round lock-step.  round_pipeline is
        # the client-side in-flight round window: 1 = today's lock-step,
        # >1 sends round N+1's request before round N's response is read
        # (the response is drained — bounded by the window — at the start
        # of a later _round call, where v4 aborts and LVE6 notices it may
        # carry are honored).  zero_rtt=False emulates a pre-v7 client:
        # no ZRT7 ad, predictions ignored (the downgrade-matrix tests and
        # the bench A/B ride this).  Both knobs are runtime-tunable
        # (autotune coordinates in multi-process mode).
        self.spec_ready_after = max(0, int(spec_ready_after))
        self.round_pipeline = max(1, int(round_pipeline))
        self.zero_rtt = bool(zero_rtt)
        # Latches once the server advertises protocol v7 (ZRT7 section).
        self.peer_zero_rtt_proto = False
        # Dispatch-safety gate, owned by the ENGINE: consuming a predicted
        # verdict means dispatching a collective BEFORE peers have seen
        # its real verdict, so the dispatch path must never block this
        # thread on device completion — a peer that still needs our next
        # round frame to learn the verdict would deadlock against our
        # blocked cycle thread.  The engine clears this when its launches
        # are synchronous (the CPU tier's serialized-launch mode, or an
        # inline-settling window); harness/bench controllers, which
        # dispatch nothing, keep the default True.
        self.spec_dispatch_ok = True
        # Slots the server predicted ready for the NEXT round (one-round
        # validity: replaced — or cleared — by every processed response),
        # and the client-side engagement streak: consecutive responses
        # that carried a usable prediction.  Consumption requires the
        # streak to reach spec_ready_after — the knob's CLIENT meaning
        # (the server's streak threshold is fixed at start): larger
        # values re-engage more conservatively after any instability,
        # since a mispredict resets the streak to zero.  This is the
        # axis the autotune coordinate actually walks.
        self._predicted: set = set()
        # Elastic streak carryover (ISSUE 12): a re-rendezvous survivor
        # seeds the consumption gate from the PREVIOUS generation's
        # engagement (spec_carry_hint()), so warm speculation re-engages
        # after the first prediction-bearing response instead of
        # relearning spec_ready_after responses from zero.  spec_seed is
        # the server-side twin (initial streak for fresh slots, rank 0
        # only).  Both default to 0 — the non-elastic behavior unchanged.
        self._pred_streak = max(0, min(int(spec_streak_hint),
                                       self.spec_ready_after))
        # Requests sent whose responses are not yet read, oldest first:
        # the consumed prediction (frozenset of slots) for speculative
        # rounds, None for plain pipelined rounds.  Never longer than
        # max(round_pipeline, 1) after a _round call returns.
        self._outstanding: List[Optional[frozenset]] = []
        # Speculation observability (bench zero_rtt_ab, /metrics, the
        # timeline counter track): hits/mispredicts resolve when the
        # deferred response validates; spec_rounds counts verdicts
        # returned without waiting (round trips saved).
        self.spec_hits = 0
        self.spec_mispredicts = 0
        self.spec_rounds = 0
        self.inflight_high_water = 0
        self.last_round_speculative = False
        # Ranks the server reported as cleanly departed (LVE6 notice
        # sections), cumulative for this controller generation.  A
        # non-empty list means the world SHRANK without a fault: the
        # engine fails world-level work with PeerLeftInterrupt (the
        # data-plane world is still the old fixed size) and the elastic
        # wrapper re-rendezvouses.  peer_leave_hook (installed by the
        # monitor agent) is called with each notice's rank list — guarded,
        # telemetry must never fail a round.
        self.left_ranks: List[int] = []
        self.peer_leave_hook = None
        # True once leave() actually put the LEAVE frame on the wire —
        # basics.shutdown() keys the elastic abrupt-teardown path off it.
        self.leave_sent = False
        # Set by interrupt() before it severs the lock-step socket: an
        # expected local teardown whose round failure must NOT be treated
        # as a peer death (engine checks it before aborting).
        self.interrupted = False
        # Deterministic fault injection (HVD_TPU_FAULT, horovod_tpu.testing
        # .faults): cached as a bound callable ONLY when armed, so the
        # unarmed hot path costs one attribute check per site.
        self._fault_fire = _faults.fire if _faults.armed() else None
        if rank == 0:
            srv_port = port if server_port is None else int(server_port)
            self._server = self._lib.hvdtpu_server_start(
                srv_port, world, ctypes.c_double(stall_warn_s),
                int(cache_capacity),
                int(self.round_timeout_s * 1000),
                self.spec_ready_after, max(0, int(spec_seed)))
            if not self._server:
                raise RuntimeError(f"Failed to start controller server on "
                                   f"port {srv_port}")
        if self._fault_fire is not None:
            self._fault_fire("connect", rank)
        # Bounded connect retries with exponential backoff + jitter
        # (HOROVOD_CONNECT_RETRIES / HOROVOD_CONNECT_BACKOFF_MS): workers
        # may start before the coordinator's server exists.  The overall
        # connect_timeout_ms budget is split across attempts; each native
        # attempt itself re-resolves DNS and re-tries the TCP connect.
        retries = max(0, int(connect_retries))
        per_ms = (connect_timeout_ms if retries == 0
                  else max(1000, int(connect_timeout_ms / (retries + 1))))

        def _connect():
            handle = self._lib.hvdtpu_client_connect(
                addr.encode(), port, rank, per_ms)
            if not handle:
                raise ConnectionError(
                    f"controller at {addr}:{port} not reachable")
            return handle

        def _on_retry(attempt, exc, delay_s):
            log.warning(
                "rank %d: %s (attempt %d/%d); retrying in %.1fs",
                rank, exc, attempt + 1, retries + 1, delay_s)

        try:
            self._client = retry_with_backoff(
                _connect, retries=retries, base_ms=connect_backoff_ms,
                exceptions=(ConnectionError,), on_retry=_on_retry)
        except ConnectionError as exc:
            self._client = None
            if self._server:
                self._lib.hvdtpu_server_stop(self._server)
            raise RuntimeError(
                f"rank {rank}: failed to connect to controller at "
                f"{addr}:{port} after {retries + 1} attempt(s)") from exc
        self._announced: set = set()
        # Response cache (reference N8 response_cache.cc): slot table
        # replicated across ranks.  (name, digest, required, datadep,
        # grouped) -> server-assigned uint32 slot; once learned, steady-
        # state announces ride a fixed-size bitvector (bit = slot pending)
        # instead of per-tensor metadata frames.  Any miss — shape/dtype
        # change (new digest), grouped<->ungrouped flip, forget(), or a
        # coordinated eviction — falls back to a full announce, which
        # (re)learns the slot.  Insertion order doubles as LRU order:
        # hits reinsert at the end, capacity trims pop from the front.
        self.cache_capacity = max(0, int(cache_capacity))
        self.cache_enabled = self.cache_capacity > 0
        self.cache_stats = ResponseCacheStats()
        self._slots: Dict[tuple, int] = {}
        self._slot_keys: Dict[int, tuple] = {}
        # Persistent-program invalidation (engine hook, ISSUE 8): called
        # with each slot id this client drops — eviction broadcast,
        # forget(), capacity trim, or slot-id reuse via a fresh adoption —
        # so the engine's slot-pinned compiled programs can never outlive
        # (or cross-serve) the slot they were pinned to.  Guarded: the
        # data-plane cache must never fail a negotiation round.
        self.slot_drop_hook = None
        # Full key tuples announced in full and awaiting a server slot.
        # The server echoes the full key in the assignment broadcast, so
        # adoption matches exactly the announced tuple — same (name,
        # digest) under a different process set (different required/
        # datadep) or grouped-ness can't cross-adopt slots.  Every full
        # announce MUST register here: a slot-bit ready verdict is only
        # resolvable if the announcer adopted the slot in the same round
        # the server learned it.
        self._awaiting_assign: set = set()
        self.bytes_sent = 0                      # telemetry (tests/timeline)
        # Monitor side-channel (protocol v3, horovod_tpu.monitor): when a
        # MonitorAgent is attached, `monitor_source()` may yield an opaque
        # snapshot blob to append to this round's request (interval-gated
        # by the agent — absent on most rounds), and `monitor_sink(blobs)`
        # receives the server's re-broadcast of every rank's fresh blobs.
        # `peer_monitor_proto` latches once the server advertises the v3
        # monitor section in a response — the agent's version gate: against
        # a pre-v3 server it stops paying frame bytes after a grace window.
        # Telemetry must NEVER fail negotiation: both callbacks are guarded.
        self.monitor_source = None
        self.monitor_sink = None
        self.on_join_epoch = None     # monitor aggregation-table flush hook
        self.monitor_bytes_sent = 0   # subset of bytes_sent (frame guard
                                      # tests subtract it)
        self.peer_monitor_proto = False
        self.rounds = 0
        self._early_ready: List[tuple] = []       # (name, digest)
        self._early_errors: Dict[str, str] = {}
        self._resp_buf = (ctypes.c_uint8 * _RESP_CAP)()
        # join protocol state (reference: hvd.join semantics).  While this
        # rank is joined, `synthesizer(name, digest)` — installed by the
        # engine — builds a zero-contribution entry for peers' collectives.
        self._join_pending = False
        self._joined = False
        self._join_event = threading.Event()
        self._join_last_rank = -1
        self._join_error: Optional[BaseException] = None
        self.synthesizer = None
        # Peer group tags → local ids, in a high id range so a synthesized
        # group can never collide with this rank's own group ids (a joining
        # rank may still have un-synchronized local entries in flight).
        self._group_tags: Dict[str, int] = {}
        self._group_tag_counter = itertools.count(1 << 30)

    # ------------------------------------------------------------- protocol
    @property
    def inflight_rounds(self) -> int:
        """Requests on the wire whose responses are not yet read (>0 only
        under speculation or ``round_pipeline > 1``)."""
        return len(self._outstanding)

    def _round(self, announces: Sequence) -> tuple:
        """announces: (name, required_ranks, digest, group, datadep, tag
        [, entry]) tuples; required 0 = world.  Tuples whose slot is known
        ride the fixed-size bitvector (the steady-state fast path); the
        sanitizer tag — when present — travels in the sparse side-channel
        so order divergence is still caught on the cached path.  The
        optional trailing entry (never on the wire) gets its learned slot
        stamped as ``cache_slot`` — the engine's persistent-program pin
        key, obtained here where the slot lookup already happened so the
        hot dispatch path never rebuilds the announce key.

        Zero-RTT warm path (protocol v7): a round whose entire announce is
        exactly the server's prediction returns the predicted verdict
        WITHOUT waiting for the response — the response is drained at the
        start of a later call, where it validates the prediction (and
        delivers any abort/leave/monitor payload one round late, bounded
        by the in-flight window).  ``round_pipeline > 1`` defers the read
        the same way without needing a prediction: the verdict then lands
        one call later, off the critical path."""
        acc_ready: List[tuple] = []
        acc_warns: List[str] = []
        acc_errors: List[tuple] = []
        acc = (acc_ready, acc_warns, acc_errors)
        depth = max(1, int(self.round_pipeline))
        # Deferred responses first: bound the in-flight window, then
        # opportunistically consume anything already buffered (refreshes
        # the prediction at ~zero wait — in the steady state the previous
        # round's response arrived while this rank computed).
        while len(self._outstanding) >= depth:
            self._drain_one(acc)
        while self._outstanding and \
                self._lib.hvdtpu_client_pending(self._client):
            self._drain_one(acc)
        full, bits, tags = [], [], []
        stats = self.cache_stats
        for a in announces:
            n, required, digest, group, datadep, tag = a[:6]
            key = (n, digest, required, datadep, group != "-1")
            slot = self._slots.get(key) if self.cache_enabled else None
            if slot is None:
                full.append(a[:6])
                if not n.startswith("\x1f"):
                    stats.misses += 1
                    # EVERY cacheable full announce registers for adoption
                    # (see _awaiting_assign comment) — even with the local
                    # cache disabled: the server may still answer through a
                    # slot bit (peers use the fast path), and resolving it
                    # needs the mapping.  cache_enabled only gates the
                    # bit-ANNOUNCE path above.  The soft cap bounds
                    # pathological digest churn; the slot table itself is
                    # LRU-bounded by cache_capacity.
                    if len(self._awaiting_assign) < (1 << 20):
                        self._awaiting_assign.add(key)
            else:
                # LRU touch: reinsert at the end of the dict order.
                self._slots.pop(key)
                self._slots[key] = slot
                bits.append(slot)
                if tag:
                    tags.append((slot, tag))
                stats.hits += 1
                if len(a) > 6 and a[6] is not None:
                    a[6].cache_slot = slot
        req = bytearray(struct.pack("<I", len(full)))
        for n, required, digest, group, datadep, tag in full:
            req += struct.pack("<H", required)
            for field in (n, digest, group, datadep, tag):
                fb = field.encode()
                req += struct.pack("<H", len(fb)) + fb
        if bits:
            nb = max(bits) // 8 + 1
            bv = bytearray(nb)
            for s in bits:
                bv[s // 8] |= 1 << (s % 8)
        else:
            nb, bv = 0, b""
        req += struct.pack("<I", nb) + bytes(bv)
        req += struct.pack("<I", len(tags))
        for slot, tag in tags:
            tb = tag.encode()
            req += struct.pack("<IH", slot, len(tb)) + tb
        # Monitor side-channel (absent on most rounds — the agent interval-
        # gates it).  A pre-v3 server stops parsing after the tag section,
        # so the trailing bytes are simply ignored there.
        self.rounds += 1
        if self.monitor_source is not None:
            try:
                blob = self.monitor_source()
            except Exception:  # noqa: BLE001 - telemetry never fails a round
                log.exception("monitor source failed")
                blob = None
            if blob:
                req += struct.pack("<II", _MON_MAGIC, len(blob)) + blob
                self.monitor_bytes_sent += 8 + len(blob)
        # Speculation decision (protocol v7): the verdict may be returned
        # without waiting only when this client's ENTIRE outstanding
        # negotiation state is a SUBSET of the predicted warm set (each
        # predicted slot is an independent "ready next round" claim, so a
        # round announcing only part of the working set — the sequential
        # per-tensor submit pattern — still qualifies) — and no full
        # announces, no sanitizer tags, no older announced-but-unresolved
        # names (whose verdict could interleave and reorder dispatch
        # across ranks), no join in any form, no unread responses (the
        # prediction would be stale).  Everything else falls back to the
        # lock-step (or plain pipelined) round.
        spec_slots = None
        if (self.zero_rtt and self.spec_ready_after > 0 and self._predicted
                and self.spec_dispatch_ok
                and self._pred_streak >= self.spec_ready_after
                and not full and not tags and bits
                and not self._outstanding
                and not self._joined and not self._join_pending
                and set(bits) <= self._predicted
                and len(bits) == len(set(bits))):
            names = set()
            for s in bits:
                key = self._slot_keys.get(s)
                if key is None:
                    names = None
                    break
                names.add(key[0])
            if names is not None and names == self._announced:
                spec_slots = frozenset(bits)
        # v5 + v6 + v7 + v4 capability hellos: FIRST request only, so
        # warm-path frames carry zero extra bytes (the frame guard asserts
        # this).  AGG5/LVE6/ZRT7 ride before FLT1 — the server's
        # abort-path capability salvage reads the frame's FINAL 8 bytes as
        # the FLT1 ad, so FLT1 must stay last.
        if self.rounds == 1:
            req += struct.pack("<II", _AGG_MAGIC, 0)
            req += struct.pack("<II", _LVE_MAGIC, 0)
            if self.zero_rtt:
                req += struct.pack("<II", _ZRT_MAGIC, 0)
            req += struct.pack("<II", _FLT_MAGIC, 0)
        if spec_slots is not None:
            # One-byte speculation confirm: this round's verdict was
            # consumed from the prediction (the announce itself still
            # rides the ordinary bitvector section above).
            req += struct.pack("<IIB", _ZRT_MAGIC, 1, 1)
        stats.full_announces += sum(1 for a in full
                                    if not a[0].startswith("\x1f"))
        stats.bit_announces += len(bits)
        self.bytes_sent += len(req)
        if self._fault_fire is not None:
            self._fault_fire("round_send", self.rank, sever=self._sever)
        # Drain a queued ABORT before sending: the server may have posted
        # the typed verdict behind the previous round's response, and a
        # send into an already-reset socket would make the kernel discard
        # the buffered frame (losing the attribution).  With responses
        # legitimately in flight (speculation/pipelining) a readable frame
        # is EXPECTED — the entry drain above already consumed what it
        # could, so skip the desync check entirely.
        if not self._outstanding and \
                self._lib.hvdtpu_client_pending(self._client):
            # NB: poll() also reports readable on EOF/POLLHUP — a dead
            # socket lands here too, and must be reported as the ordinary
            # peer-death failure, not as a protocol bug.
            rc, _ = self._recv_salvaging_abort(1000)
            if rc == -2:
                self._raise_overflow()
            if rc < 0:
                self._raise_unattributed_failure(f"rc={rc}")
            raise HorovodInternalError(
                "controller protocol desync: unsolicited frame before the "
                "round request (rc={})".format(rc))
        buf = (ctypes.c_uint8 * len(req)).from_buffer(req) if req else \
            (ctypes.c_uint8 * 0)()
        rc = self._lib.hvdtpu_client_send(self._client, buf, len(req))
        if rc < 0:
            # Send failed — the socket died between rounds.  A typed abort
            # may still be buffered locally; salvage it for attribution.
            self._recv_salvaging_abort(250)
            self._raise_unattributed_failure(f"send rc={rc}")
        if self._fault_fire is not None:
            self._fault_fire("mid_round_exit", self.rank,
                             sever=self._sever)
            self._fault_fire("round_recv", self.rank, sever=self._sever)
        self._outstanding.append(spec_slots)
        self.last_round_speculative = spec_slots is not None
        if spec_slots is not None:
            # Zero-RTT: return the predicted verdict NOW; the response is
            # validated at the start of a later round.  Verdict order is
            # slot-ascending — identical to the ready-bitvector
            # reconstruction rule every rank applies, so speculating and
            # lock-stepping ranks dispatch in the same order.
            self.spec_rounds += 1
            self._predicted = set()            # one-round validity: consumed
            for s in sorted(spec_slots):
                key = self._slot_keys.get(s)
                if key is not None:
                    acc_ready.append((key[0], key[1], "-1"))
            if len(self._outstanding) > self.inflight_high_water:
                self.inflight_high_water = len(self._outstanding)
            return acc
        # Lock-step (depth 1): read this round's response now.  Pipelined
        # (depth > 1): leave up to depth-1 responses in flight — their
        # verdicts land at a later call, off the critical path.
        while len(self._outstanding) >= depth:
            self._drain_one(acc)
        # High-water of the DEFERRED window: what is still unread when the
        # round returns (a lock-step round always returns at 0).
        if len(self._outstanding) > self.inflight_high_water:
            self.inflight_high_water = len(self._outstanding)
        return acc

    def _drain_one(self, acc, timeout_ms: Optional[int] = None):
        """Read and process the OLDEST outstanding response, folding its
        verdicts into ``acc`` = (ready, warns, errors).  All the
        lock-step recv classification (typed abort salvage, round
        timeout, overflow, unattributed death) lives here so deferred
        reads fail exactly like synchronous ones — just up to one round
        later, bounded by the in-flight window."""
        spec_slots = self._outstanding[0]
        # Client-side wall-clock deadline (2x the server's per-round
        # deadline — see __init__): the backstop for a wedged coordinator.
        if timeout_ms is None:
            timeout_ms = int(self.round_timeout_s * 2000)
        rc, data = self._recv_salvaging_abort(timeout_ms)
        if rc == -3:
            msg = (f"HVD303 negotiation round timed out after "
                   f"{self.round_timeout_s * 2:g}s (HOROVOD_ROUND_TIMEOUT_S"
                   f"={self.round_timeout_s:g}); the coordinator or a peer "
                   f"rank is wedged")
            extra = self._enrich(None)
            if extra:
                msg += "\n" + extra
            raise RoundTimeoutError(msg, timeout_s=self.round_timeout_s * 2)
        if rc == -2:
            self._raise_overflow()
        if rc < 0:
            # ControlPlaneError subclasses HorovodInternalError, so elastic
            # run wrappers still catch-and-restore (SURVEY.md §3.4).
            self._raise_unattributed_failure(f"rc={rc}")
        self._outstanding.pop(0)
        ready, warns, errors = self._parse_response(data, spec_slots)
        acc[0].extend(ready)
        acc[1].extend(warns)
        acc[2].extend(errors)

    def _parse_response(self, data: bytes,
                        spec_slots: Optional[frozenset] = None) -> tuple:
        """Decode one response frame, applying every side effect (slot
        adoption, coordinated evictions, capability latches, monitor
        sink, leave notices, next-round prediction).  ``spec_slots``
        non-None marks the round as speculatively consumed: its slot
        verdicts were already delivered at send time, so they are
        filtered here and only VALIDATE the prediction."""
        off = 0

        def read_list():
            nonlocal off
            (n,) = struct.unpack_from("<I", data, off)
            off += 4
            out = []
            for _ in range(n):
                (ln,) = struct.unpack_from("<H", data, off)
                off += 2
                out.append(data[off:off + ln].decode())
                off += ln
            return out

        def read_tuple(k):
            nonlocal off
            (n,) = struct.unpack_from("<I", data, off)
            off += 4
            out = []
            for _ in range(n):
                fields = []
                for _f in range(k):
                    (ln,) = struct.unpack_from("<H", data, off)
                    off += 2
                    fields.append(data[off:off + ln].decode())
                    off += ln
                out.append(tuple(fields))
            return out

        # ready: (name, digest, group) — digest + group feed the joined
        # rank's synthesized entries; errors: (name, message).
        ready = read_tuple(3)
        warns = read_list()
        errors = read_tuple(2) if off < len(data) else []
        # Slot assignments: adopt those matching a tuple this client
        # announced in full (the server broadcasts to every rank).
        # Processed BEFORE the ready bitvector so a slot assigned and made
        # ready in the same round resolves.
        if off < len(data):
            (n_assign,) = struct.unpack_from("<I", data, off)
            off += 4
            for _ in range(n_assign):
                fields = []
                for _f in range(3):
                    (ln,) = struct.unpack_from("<H", data, off)
                    off += 2
                    fields.append(data[off:off + ln].decode())
                    off += ln
                (required, grouped, slot) = struct.unpack_from(
                    "<HHI", data, off)
                off += 8
                name, digest, datadep = fields
                key = (name, digest, required, datadep, bool(grouped))
                if key in self._awaiting_assign:
                    self._awaiting_assign.discard(key)
                    self._adopt_slot(key, slot)
        # Ready bitvector: slot verdicts, appended after the string
        # verdicts in increasing slot order.  Every client applies the
        # same rule, so the reconstructed order is identical on all ranks
        # (which is all the engine's deterministic batching needs).
        # Unknown slots are other process sets' tensors — not ours.
        # Speculatively consumed slots (protocol v7) were delivered at
        # send time: here they only validate the prediction.
        actual_bits: set = set()
        if off < len(data):
            (nb,) = struct.unpack_from("<I", data, off)
            off += 4
            bv = data[off:off + nb]
            off += nb
            for i in range(nb * 8):
                if not (bv[i // 8] >> (i % 8)) & 1:
                    continue
                actual_bits.add(i)
                if spec_slots is not None and i in spec_slots:
                    continue
                key = self._slot_keys.get(i)
                if key is not None:
                    ready.append((key[0], key[1], "-1"))
        if spec_slots is not None:
            if spec_slots <= actual_bits:
                self.spec_hits += 1
            else:
                # Mispredict: a predicted slot did not go ready (a rank
                # skipped a cycle, or a slot-invalidation event landed).
                # The early-consumed verdict needs no repair — our announce
                # stays pending server-side and the late real verdict is
                # absorbed by this name's next entry — but speculation
                # disengages (the server reset the slot's streak; we drop
                # any stale prediction) until the streak rebuilds through
                # normal full rounds.
                self.spec_mispredicts += 1
                self._predicted = set()
                self._pred_streak = 0
        # Coordinated evictions: drop the named slots so this table can
        # never diverge from the server's (or any peer's).
        if off < len(data):
            (n_evict,) = struct.unpack_from("<I", data, off)
            off += 4
            for _ in range(n_evict):
                (slot,) = struct.unpack_from("<I", data, off)
                off += 4
                # Server-authoritative count: a local capacity trim may
                # have dropped the slot already (invalidations covered
                # that); the eviction still happened fleet-wide.
                self.cache_stats.evictions += 1
                self._predicted.discard(slot)
                key = self._slot_keys.pop(slot, None)
                if key is not None:
                    self._slots.pop(key, None)
                    self.cache_stats.invalidations += 1
                self._notify_slot_drop(slot)
        # Trailing sections, walked order-agnostically (mirroring the
        # server's generic request-side walk, so MON1 and FLT1 compose in
        # either order).  MON1 (protocol v3): the server's re-broadcast of
        # this round's fleet snapshots.  FLT1 (protocol v4, round 1's
        # response only): the server can send us typed ABORT frames
        # instead of blind socket severs.  Each magic doubles as the
        # capability advertisement its version gate latches on.  An
        # unknown magic stops the walk: MON1 carries no section-length
        # field, so a client this old cannot skip sections it does not
        # understand (a future section must be appended after these).
        saw_prediction = False
        while off + 8 <= len(data):
            (magic,) = struct.unpack_from("<I", data, off)
            if magic == _MON_MAGIC:
                off += 4
                (n_blob,) = struct.unpack_from("<I", data, off)
                off += 4
                blobs = []
                for _ in range(n_blob):
                    (mr, ln) = struct.unpack_from("<II", data, off)
                    off += 8
                    blobs.append((mr, data[off:off + ln]))
                    off += ln
                self.peer_monitor_proto = True
                if blobs and self.monitor_sink is not None:
                    try:
                        self.monitor_sink(blobs)
                    except Exception:  # noqa: BLE001 - telemetry only
                        log.exception("monitor sink failed")
            elif magic == _FLT_MAGIC:
                off += 8  # magic + reserved u32 (always 0)
                self.peer_fault_proto = True
            elif magic == _AGG_MAGIC:
                off += 8  # magic + reserved u32 (always 0)
                self.peer_hier_proto = True
            elif magic == _LVE_MAGIC:
                # Clean-LEAVE section (protocol v6): the payload-bearing
                # form — (magic, len, n_left, ranks) — unlike the bare
                # v4/v5 ads, so an empty round-1 section IS the server's
                # capability ad and a non-empty one is a leave notice.
                (ln,) = struct.unpack_from("<I", data, off + 4)
                off += 8
                end = off + ln
                self.peer_leave_proto = True
                n_left = 0
                if ln >= 4:
                    (n_left,) = struct.unpack_from("<I", data, off)
                    off += 4
                ranks = []
                for _ in range(n_left):
                    (r,) = struct.unpack_from("<I", data, off)
                    ranks.append(r)
                    off += 4
                off = end
                if ranks:
                    self.left_ranks = sorted(set(self.left_ranks) |
                                             set(ranks))
                    h = self.peer_leave_hook
                    if h is not None:
                        try:
                            h(ranks)
                        except Exception:  # noqa: BLE001 - telemetry only
                            log.exception("peer-leave hook failed")
            elif magic == _ZRT_MAGIC and self.zero_rtt:
                # Zero-RTT prediction section (protocol v7): the slots the
                # server predicts ready NEXT round (empty on round 1 — the
                # capability ad).  Adopted verbatim: the speculation
                # decision requires an exact match against our own next
                # announce, so an unknown slot in here simply disables
                # speculation for that round.  A pre-v7 client (zero_rtt
                # False) stops its walk here, exactly like an unknown
                # magic.
                (ln,) = struct.unpack_from("<I", data, off + 4)
                off += 8
                end = off + ln
                self.peer_zero_rtt_proto = True
                n_pred = 0
                if ln >= 4:
                    (n_pred,) = struct.unpack_from("<I", data, off)
                    off += 4
                pred = set()
                for _ in range(n_pred):
                    (s,) = struct.unpack_from("<I", data, off)
                    pred.add(s)
                    off += 4
                off = end
                self._predicted = pred
                saw_prediction = bool(pred)
            else:
                break
        if saw_prediction:
            self._pred_streak += 1
        else:
            # Predictions are one-round-valid: a response without a ZRT7
            # section (spec off, streak reset, old server, mixed-version
            # fleet) expires any stale one — and the engagement streak
            # restarts with the next prediction run.
            self._predicted = set()
            self._pred_streak = 0
        return ready, warns, errors

    # ------------------------------------------------- fault handling (v4)
    @staticmethod
    def _parse_abort(data: bytes) -> Optional[tuple]:
        """``(dead_ranks, reason)`` when ``data`` is a typed ABORT frame
        (escape word + "ABT4" magic), else None.  The escape word
        0xFFFFFFFF is an impossible n_ready, so the check is unambiguous
        against every normal response."""
        if len(data) < 12:
            return None
        esc, magic = struct.unpack_from("<II", data, 0)
        if esc != _ABORT_ESCAPE or magic != _ABORT_MAGIC:
            return None
        (n_dead,) = struct.unpack_from("<I", data, 8)
        off = 12
        ranks = []
        for _ in range(n_dead):
            (r,) = struct.unpack_from("<I", data, off)
            ranks.append(r)
            off += 4
        (ln,) = struct.unpack_from("<H", data, off)
        off += 2
        reason = data[off:off + ln].decode(errors="replace")
        return ranks, reason

    def _recv_salvaging_abort(self, timeout_ms: int):
        """One ``client_recv`` that raises the typed ``PeerFailureError``
        when the frame is a v4 ABORT; otherwise returns ``(rc, data)``
        for the caller to classify (``rc < 0``: dead / overflowed /
        timed-out socket — see ``hvdtpu_client_recv``).  All of
        ``negotiate()``'s salvage points (pre-send drain, failed send,
        main response) funnel through here so the abort handling cannot
        drift between them."""
        rc = self._lib.hvdtpu_client_recv(
            self._client, self._resp_buf, _RESP_CAP, timeout_ms)
        data = bytes(self._resp_buf[:rc]) if rc > 0 else b""
        abort = self._parse_abort(data)
        if abort is not None:
            self._raise_peer_failure(*abort)
        return rc, data

    def _enrich(self, dead_ranks: Optional[List[int]]) -> str:
        """Monitor-sourced attribution block (snapshot ages, ledger tails)
        for HVD303 errors; empty without an agent.  Telemetry must never
        mask the original failure — guarded."""
        if self.fault_enricher is None:
            return ""
        try:
            return self.fault_enricher(dead_ranks) or ""
        except Exception:  # noqa: BLE001 - attribution is best-effort
            log.exception("fault enricher failed")
            return ""

    def _raise_peer_failure(self, ranks: List[int], reason: str):
        msg = (f"HVD303 control-plane peer failure: the coordinator "
               f"declared rank(s) {sorted(ranks)} dead: {reason}")
        extra = self._enrich(ranks)
        if extra:
            msg += "\n" + extra
        raise PeerFailureError(msg, dead_ranks=ranks, reason=reason)

    def _raise_overflow(self):
        """A response larger than the fixed receive buffer (native rc=-2)
        is a protocol/sizing bug, NOT a peer failure: deliberately a plain
        RuntimeError — a ControlPlaneError (or any HorovodInternalError)
        would send the elastic run wrapper into a restore loop that hits
        the identical overflow every round, while telling the operator
        peers are dying."""
        raise RuntimeError(
            f"negotiation response exceeded the fixed "
            f"{_RESP_CAP // (1024 * 1024)}MB receive buffer (_RESP_CAP); "
            f"this is a protocol/sizing bug, not a peer failure — reduce "
            f"the per-round announce burst or raise _RESP_CAP")

    def _raise_unattributed_failure(self, detail: str):
        """Peer death inferred from a severed socket with no salvageable
        abort verdict naming the culprit.  Still typed (ControlPlaneError,
        so the engine runs its clean abort instead of leaving the
        InflightRing waiting on a dead world) and still monitor-enriched —
        with no dead-rank list, the stalest snapshot is the prime suspect."""
        msg = (f"HVD303 controller round failed ({detail}); a peer likely "
               f"died mid-negotiation (unattributed: no abort verdict was "
               f"salvageable)")
        extra = self._enrich(None)
        if extra:
            msg += "\n" + extra
        raise PeerFailureError(msg, dead_ranks=[])

    def _notify_slot_drop(self, slot: int):
        h = self.slot_drop_hook
        if h is not None:
            try:
                h(slot)
            except Exception:  # noqa: BLE001 - data-plane cache only
                log.exception("slot-drop hook failed")

    def _adopt_slot(self, key: tuple, slot: int):
        old = self._slot_keys.pop(slot, None)
        if old is not None:
            self._slots.pop(old, None)
            # Slot-id reuse: a program pinned to the OLD tuple must not
            # serve the new one (its digest differs by construction) —
            # nor may a prediction made for the old tuple (v7).
            self._predicted.discard(slot)
            self._notify_slot_drop(slot)
        self._trim_slots(len(self._slots) + 1)
        self._slots[key] = slot
        self._slot_keys[slot] = key

    def _trim_slots(self, size: Optional[int] = None):
        """Enforce the (runtime-tunable) local capacity, LRU-first.  Slots
        whose tensor is still in flight are skipped: dropping one would
        make a later slot-bit ready verdict unresolvable."""
        if size is None:
            size = len(self._slots)
        if size <= max(1, self.cache_capacity):
            return
        excess = size - max(1, self.cache_capacity)
        for lru_key in list(self._slots):
            if excess <= 0:
                break
            if lru_key[0] in self._announced:
                continue
            lru_slot = self._slots.pop(lru_key)
            self._slot_keys.pop(lru_slot, None)
            self._predicted.discard(lru_slot)
            self.cache_stats.invalidations += 1
            self._notify_slot_drop(lru_slot)
            excess -= 1

    # ---------------------------------------------------------- engine API
    @staticmethod
    def _wire_name(e) -> str:
        # Namespace by process set so the same tensor name used concurrently
        # by two disjoint sets can't merge their readiness on the server
        # (which keys pending state by wire name alone).
        ps_id = getattr(e, "process_set_id", 0)
        return f"{ps_id}\x1f{e.name}" if ps_id else e.name

    @staticmethod
    def _digest(e) -> str:
        """Submission consistency digest: op kind, dtype, per-rank shape,
        reduce op, root, scale factors, wire compression — what the
        reference's Request carries for the controller's shape/dtype checks
        (SURVEY.md N2/N5).  Step-invariant by construction: the sanitizer's
        per-submission tag travels in the announce's separate ``tag`` field
        (the server folds it back into its mismatch comparison), so the
        digest can key a response-cache slot that stays valid across
        steps even in sanitizer mode."""
        t = getattr(e, "tensor", None)
        if t is None:
            return "barrier"
        shape = tuple(t.shape[1:]) if len(t.shape) else ()
        ct = getattr(e, "ctype", None)
        op = getattr(e, "reduce_op", None)
        parts = [ct.value if ct is not None else "op",
                 str(t.dtype), str(shape)]
        if op is not None:
            parts.append(op.name)
        parts.append(str(getattr(e, "root_rank", 0)))
        # Scale factors shape the fused program (they are in the engine's
        # fusion key), so divergence would desync batching across ranks.
        # Deliberately NOT here: group_id — local group counters can drift
        # across ranks (uneven join epochs), so it travels in the announce's
        # separate `group` field, outside the mismatch comparison.
        parts.append(str(getattr(e, "prescale_factor", None)))
        parts.append(str(getattr(e, "postscale_factor", None)))
        # Wire compression shapes the fused program (cast-down before the
        # reduce, cast-up after): divergence across ranks would execute
        # mismatched programs, so it is part of the consistency check.
        # Joined ranks parse digest fields positionally and rely on this
        # slot being parts[7] (see engine._synthesize_join_entry).
        parts.append(str(getattr(e, "compression", None) or "none"))
        # ZeRO-sharded dimension (ISSUE 15): appended ONLY when set, so
        # every flat digest stays byte-identical to the established
        # protocol (and pinned response-cache slots survive the upgrade).
        # A sharded reduce-scatter/allgather program differs from the
        # ordinary one of the same shapes, so flag divergence across
        # ranks must fail the consistency check, not execute.  Joined
        # ranks read it positionally at parts[8].
        # "sharded-full" (ISSUE 18) is the FSDP plane's token: the full-
        # parameter-sharded reduce-scatter/allgather programs must never
        # cross-serve the state-only-sharded (ISSUE 15) ones.  The
        # prefetch/hierarchical flags deliberately do NOT ride the digest
        # (fusion-key-only, results bitwise-identical either way).
        sh = getattr(e, "sharded", False)
        if sh == "full":
            parts.append("sharded-full")
        elif sh:
            parts.append("sharded")
        return "|".join(parts)

    @staticmethod
    def _datadep(e) -> str:
        """Which ranks' REAL data this collective needs: '-1' none
        (reductions/barrier — identity contributions are valid), '-2' every
        rank (allgather/alltoall), or the broadcast root.  The server
        errors instead of granting joined-credit when the needed rank has
        joined."""
        ct = getattr(e, "ctype", None)
        v = getattr(ct, "value", "")
        if v in ("allgather", "alltoall"):
            return "-2"
        if v == "broadcast":
            return str(getattr(e, "root_rank", 0))
        return "-1"

    def negotiate(self, entries: List) -> tuple:
        """One negotiation round.  Takes this cycle's drained entries (they
        may include requeued ones), announces the new names + digests, and
        returns ``(ready, errored)``: the subset ready everywhere in the
        server's global order, and ``(entry, message)`` pairs for per-tensor
        negotiation failures (digest mismatch across ranks)."""
        if self._fault_fire is not None:
            self._fault_fire("pre_announce", self.rank, sever=self._sever)
        by_name: Dict[str, object] = {self._wire_name(e): e for e in entries}
        new = []
        for n, e in by_name.items():
            if n in self._announced:
                continue
            required = 0
            ps_id = getattr(e, "process_set_id", 0)
            if ps_id:
                # Sub-process-set collectives are only announced by member
                # ranks; the server readiness threshold is the set size.
                from .basics import _get_state
                required = _get_state().process_set_table.get(ps_id).size()
            new.append((n, required, self._digest(e),
                        str(getattr(e, "group_id", -1)), self._datadep(e),
                        getattr(e, "sanitizer_tag", None) or "", e))
        self._announced.update(n for n, *_ in new)
        self._trim_slots()
        if self._join_pending:
            self._join_pending = False
            self._joined = True
            new.append(("\x1f__join__", 0, "", "-1", "-1", ""))
        ready, warns, errors = self._round(new)
        for w in warns:
            log.warning("controller: %s", w)
        # The engine requeues not-ready entries, so every announced name
        # reappears in `entries` each cycle; _early_ready only fills in the
        # (defensive) case of a ready verdict arriving before the local
        # requeue is drained.
        ready = self._early_ready + ready
        self._early_ready = []
        out = []
        for name, digest, group in ready:
            if name == "\x1f__all_joined__":
                # Every rank joined: end the join epoch (digest = last
                # joining rank) and unblock the join() caller.
                self._joined = False
                self._join_last_rank = int(digest)
                if self.on_join_epoch is not None:
                    # Monitor aggregation-table flush: snapshots captured
                    # while the world was uneven must not survive the
                    # epoch (mirrors the server's slot-table flush).
                    try:
                        self.on_join_epoch(self._join_last_rank)
                    except Exception:  # noqa: BLE001 - telemetry only
                        log.exception("join-epoch monitor hook failed")
                self._join_event.set()
                continue
            e = by_name.pop(name, None)
            if e is None:
                # The server broadcasts ready verdicts to every rank; a name
                # this rank never announced is either another process set's
                # collective (wire names carry a "\x1f" set prefix — not
                # ours, drop) or — while this rank is JOINED — a world
                # collective peers submitted, for which we synthesize an
                # identity contribution (reference join semantics).
                if name in self._announced:
                    self._early_ready.append((name, digest, group))
                elif self._joined and "\x1f" not in name \
                        and self.synthesizer is not None:
                    out.append(self.synthesizer(name, digest,
                                                self._group_tag_id(group)))
                continue
            self._announced.discard(name)
            out.append(e)
        # Per-tensor errors: fail the local entry (waiters see the exception
        # from synchronize()); re-broadcasts for entries already failed (or
        # another set's tensors) are dropped.  _early_errors covers an error
        # verdict racing ahead of the local requeue drain, like _early_ready.
        errored = []
        for name, msg in dict(self._early_errors).items():
            e = by_name.pop(name, None)
            if e is not None:
                del self._early_errors[name]
                self._announced.discard(name)
                errored.append((e, msg))
        for name, msg in errors:
            e = by_name.pop(name, None)
            if e is None:
                if name in self._announced:
                    self._early_errors[name] = msg
                continue
            self._announced.discard(name)
            errored.append((e, msg))
        return out, errored

    def slot_of(self, e) -> int:
        """The response-cache slot assigned to an entry's announce key, or
        -1 while unlearned.  The compact cross-rank correlation id the
        trace spans carry beside the cycle id (``horovod_tpu.trace``):
        slots are server-assigned, so the same tensor has the same slot on
        every rank.  Read-only — never touches the LRU order."""
        ps_id = getattr(e, "process_set_id", 0)
        required = 0
        if ps_id:
            from .basics import _get_state
            required = _get_state().process_set_table.get(ps_id).size()
        key = (self._wire_name(e), self._digest(e), required,
               self._datadep(e), getattr(e, "group_id", -1) != -1)
        return self._slots.get(key, -1)

    def forget(self, e):
        """Drop all negotiation bookkeeping for an entry failed locally
        (e.g. group-abort) so a retry under the same name renegotiates from
        scratch instead of consuming a stale ready/error verdict.  Also an
        explicit response-cache invalidation: the name's slots are dropped,
        so the retry takes the full-announce path (and relearns)."""
        n = self._wire_name(e)
        self._announced.discard(n)
        self._early_errors.pop(n, None)
        self._early_ready = [t for t in self._early_ready if t[0] != n]
        for key in [k for k in self._slots if k[0] == n]:
            slot = self._slots.pop(key)
            self._slot_keys.pop(slot, None)
            self._predicted.discard(slot)
            self.cache_stats.invalidations += 1
            self._notify_slot_drop(slot)
        self._awaiting_assign = {k for k in self._awaiting_assign
                                 if k[0] != n}

    def _group_tag_id(self, tag: str) -> int:
        """Server group tags ("<first-announcer-rank>:<their gid>"; "-1"
        ungrouped) → local int group ids for the engine's batch clustering.
        Distinct tags get distinct ids, so two different peers' groups can
        never merge on a joined rank."""
        if tag == "-1":
            return -1
        gid = self._group_tags.get(tag)
        if gid is None:
            gid = self._group_tags[tag] = next(self._group_tag_counter)
        return gid

    # --------------------------------------------------------------- join
    def request_join(self):
        """Mark this rank joined as of the next negotiation round
        (reference: hvd.join).  The engine keeps cycling; peers' world
        collectives execute here with synthesized zero contributions until
        every rank has joined."""
        self._join_event.clear()
        self._join_pending = True

    def join_wait(self, timeout: Optional[float] = None) -> int:
        """Block until every rank joined; returns the last rank to join.

        Contract: the return value is always the last joining rank (an
        ``int >= 0``) — never a sentinel.  If the all-joined verdict does
        not arrive within ``timeout`` seconds, raises
        :class:`~.exceptions.JoinTimeoutError` (a ``TimeoutError``
        subclass, so existing handlers keep working); the join stays
        pending and a later ``join_wait`` may still succeed."""
        if not self._join_event.wait(timeout):
            raise JoinTimeoutError(
                f"join() did not complete within {timeout}s: some ranks "
                f"have not joined (the negotiation keeps running; call "
                f"join_wait again to keep waiting)")
        if self._join_error is not None:
            raise self._join_error
        return self._join_last_rank

    def spec_carry_hint(self) -> int:
        """The streak seed a re-rendezvous SURVIVOR carries into the next
        generation (ISSUE 12 elastic streak carryover): non-zero only when
        speculation was armed, advertised by the server, and actually
        engaged (at least one hit) in this generation.  The elastic
        re-init passes it as both the new server's ``spec_seed`` (rank 0)
        and the new client's ``spec_streak_hint``, so the warm path
        re-engages in O(1) rounds instead of relearning from zero."""
        if (self.spec_ready_after <= 0 or not self.peer_zero_rtt_proto
                or self.spec_hits <= 0):
            return 0
        # A live engagement streak carries verbatim; a generation that
        # engaged but was mid-rebuild carries the full threshold anyway —
        # the workload proved stable enough to speculate at least once.
        return max(1, min(self._pred_streak or self.spec_ready_after,
                          self.spec_ready_after))

    def fail_join(self, exc: BaseException):
        """Fail any pending (and every future) ``join_wait`` with ``exc``.

        Part of the engine abort's no-waiter-may-hang invariant: once the
        control plane is down, the all-joined verdict can never arrive —
        a ``hvd.join()`` blocked with ``timeout=None`` would otherwise
        wait forever.  Sticky: this controller generation is dead."""
        self._join_error = exc
        self._join_event.set()

    def leave(self) -> bool:
        """Announce this rank's clean departure (protocol v6): one typed
        LEAVE frame on the lock-step socket, sent IN PLACE of the next
        round frame, immediately before the sever.

        The server drops the rank from the gather with no dead-peer
        verdict — survivors get a leave notice instead of an HVD303 abort
        — and aborts (typed, naming us) only if we still have outstanding
        negotiated work, which is why the frame is refused locally while
        ``_announced`` is non-empty: a LEAVE that would abort the fleet is
        worse than the legacy sever's staggered-shutdown heuristic.

        Caller contract: the engine's cycle thread must be quiesced (no
        lock-step round in flight — ``engine.quiesce()``); version-gated
        on the server's round-1 LVE6 ad, so against a pre-v6 coordinator
        this is a no-op and the sever keeps its legacy semantics.
        Returns True when the frame actually went on the wire."""
        if self._client is None or self.interrupted or self.leave_sent:
            return False
        # Responses still in flight (speculation / round_pipeline > 1) are
        # drained first: the LEAVE frame must be the next thing the server
        # reads from a QUIET socket, and a deferred response may carry the
        # leave-relevant latches (peer_leave_proto on round 1) or a typed
        # abort that makes leaving moot.  Bounded even with the round
        # timeout disabled — a clean shutdown must not block forever on a
        # response a dead coordinator will never finish — and a typed
        # verdict surfacing here is LOGGED with its attribution before the
        # fall-back to the legacy sever: consuming the frame consumed the
        # fleet's only copy of the dead-rank list.
        try:
            acc = ([], [], [])
            while self._outstanding:
                self._drain_one(
                    acc, timeout_ms=int(self.round_timeout_s * 2000) or 5000)
            # Verdicts a deferred response delivered here are parked for
            # the next negotiate (the engine may keep cycling if the
            # leave is refused below) — never dropped.
            for name, digest, group in acc[0]:
                if name in self._announced:
                    self._early_ready.append((name, digest, group))
            for name, msg in acc[2]:
                if name in self._announced:
                    self._early_errors[name] = msg
        except Exception as exc:  # noqa: BLE001 - dead world: legacy sever
            log.warning(
                "clean LEAVE abandoned: draining the in-flight round "
                "window failed (%s); falling back to the legacy sever",
                exc)
            return False
        if (not self.peer_leave_proto
                or self._announced or self._joined or self._join_pending):
            return False
        req = struct.pack("<II", _LEAVE_ESCAPE, _LVE_MAGIC)
        buf = (ctypes.c_uint8 * len(req)).from_buffer_copy(req)
        rc = self._lib.hvdtpu_client_send(self._client, buf, len(req))
        self.leave_sent = rc == 0
        return self.leave_sent

    def interrupt(self):
        """Unblock any thread stuck in a lock-step round (socket shutdown,
        no free) — call before stopping the engine thread.  Sets
        ``interrupted`` first: the severed socket makes the in-flight
        round raise exactly like a peer death, and the engine's cycle
        handler uses the flag to tell expected teardown apart from a
        real HVD303 fault (no spurious abort/log/health flip on every
        clean shutdown)."""
        self.interrupted = True
        self._sever()

    def _sever(self):
        """Abruptly shut down the client socket without marking the
        teardown expected — the fault harness's ``econnreset`` action uses
        this so an injected sever still surfaces as a real HVD303 fault
        on the severed rank."""
        if self._client:
            self._lib.hvdtpu_client_interrupt(self._client)

    def shutdown(self):
        if self._client:
            self._lib.hvdtpu_client_close(self._client)
            self._client = None
        if self._server:
            self._lib.hvdtpu_server_stop(self._server)
            self._server = None
