"""Environment-variable configuration surface.

TPU-native equivalent of the reference's env parser
(``horovod/common/utils/env_parser.cc``) and the ``HOROVOD_*`` config surface
described in SURVEY.md §5 ("Config/flag system").  Same two-layer pattern:
env vars are the core config; the launcher (``horovod_tpu/runner``) forwards
CLI/YAML settings to workers as env vars.

We accept both the reference's ``HOROVOD_*`` names (so existing user scripts /
run-books keep working) and ``HVD_TPU_*`` overrides.
"""

from __future__ import annotations

import dataclasses
import os
from typing import Optional


def _env(name: str, default: Optional[str] = None) -> Optional[str]:
    """Look up HVD_TPU_<name> then HOROVOD_<name>."""
    for prefix in ("HVD_TPU_", "HOROVOD_"):
        val = os.environ.get(prefix + name)
        if val is not None:
            return val
    return default


def _env_int(name: str, default: int) -> int:
    val = _env(name)
    if val is None or val == "":
        return default
    try:
        return int(val)
    except ValueError:
        raise ValueError(f"Invalid integer for HOROVOD_{name}: {val!r}")


def _env_float(name: str, default: float) -> float:
    val = _env(name)
    if val is None or val == "":
        return default
    try:
        return float(val)
    except ValueError:
        raise ValueError(f"Invalid float for HOROVOD_{name}: {val!r}")


def _env_bool(name: str, default: bool) -> bool:
    val = _env(name)
    if val is None or val == "":
        return default
    return val.strip().lower() in ("1", "true", "yes", "on")


@dataclasses.dataclass
class Config:
    """Runtime configuration, parsed once at ``init()``.

    Field-by-field mapping to the reference env vars (SURVEY.md §2a N24, §5):

    - ``fusion_threshold_bytes``   <- HOROVOD_FUSION_THRESHOLD (default 64 MB)
    - ``cycle_time_ms``            <- HOROVOD_CYCLE_TIME
    - ``cache_capacity``           <- HOROVOD_CACHE_CAPACITY (fused program
      cache)
    - ``response_cache_capacity``  <- HOROVOD_RESPONSE_CACHE_CAPACITY
      (negotiation response cache: the steady-state bitvector fast path)
    - ``pipeline_chunk_bytes``     <- HOROVOD_PIPELINE_CHUNK (fused-reduce
      chunk size for pipelined cast/reduce/cast; 0 = single chunk)
    - ``max_inflight``             <- HOROVOD_MAX_INFLIGHT (bounded window
      of dispatched-but-unsettled fused batches, multi-process mode)
    - ``fast_lane_threshold_bytes``<- HOROVOD_FAST_LANE_THRESHOLD (latency
      fast lane: sub-threshold allreduces skip the fusion buffer; 0 = off)
    - ``partition_threshold_bytes``<- HOROVOD_PARTITION_THRESHOLD
      (ByteScheduler-style split of huge tensors into preemptible
      sub-tensors; 0 = off)
    - ``timeline_filename``        <- HOROVOD_TIMELINE
    - ``timeline_mark_cycles``     <- HOROVOD_TIMELINE_MARK_CYCLES
    - ``stall_check_time_s``       <- HOROVOD_STALL_CHECK_TIME
    - ``stall_shutdown_time_s``    <- HOROVOD_STALL_SHUTDOWN_TIME
    - ``stall_check_disable``      <- HOROVOD_STALL_CHECK_DISABLE
    - ``hierarchical_allreduce``   <- HOROVOD_HIERARCHICAL_ALLREDUCE
    - ``hierarchical_allgather``   <- HOROVOD_HIERARCHICAL_ALLGATHER
    - ``hierarchical_broadcast``   <- HOROVOD_HIERARCHICAL_BROADCAST
    - ``hier_threshold_bytes``     <- HOROVOD_HIER_THRESHOLD (flat-vs-
      two-level payload crossover; 0 = always two-level when armed)
    - ``slice_map``                <- HOROVOD_SLICE_MAP (explicit slice
      membership for CPU/simulated worlds; see parallel/topology.py)
    - ``sharded_params``           <- HOROVOD_SHARDED_PARAMS (ZeRO-3/FSDP:
      DistributedOptimizer defaults to sharded="full")
    - ``prefetch_depth``           <- HOROVOD_PREFETCH_DEPTH (FSDP
      parameter-gather buckets in flight ahead of consumption)
    - ``autotune``                 <- HOROVOD_AUTOTUNE
    - ``autotune_log``             <- HOROVOD_AUTOTUNE_LOG
    - ``autotune_warmup_samples``  <- HOROVOD_AUTOTUNE_WARMUP_SAMPLES
    - ``autotune_steps_per_sample``<- HOROVOD_AUTOTUNE_STEPS_PER_SAMPLE
    - ``autotune_max_evals``       <- HOROVOD_AUTOTUNE_MAX_EVALS
    - ``log_level``                <- HOROVOD_LOG_LEVEL
    - ``batch_d2d_memcopies``      <- HOROVOD_BATCH_D2D_MEMCOPIES

    TPU-specific additions:

    - ``num_collective_streams``: number of parallel eager-dispatch lanes
      (analogue of HOROVOD_NUM_NCCL_STREAMS).
    - ``donate_fusion_buffers``: use XLA buffer donation for fused buffers.
    - ``mesh_axis_name``: the mesh axis spanned by the "hvd" world.
    """

    fusion_threshold_bytes: int = 64 * 1024 * 1024
    cycle_time_ms: float = 1.0
    cache_capacity: int = 1024
    cache_enabled: bool = True
    # Negotiation response cache (HOROVOD_RESPONSE_CACHE_CAPACITY, upstream
    # HOROVOD_CACHE_CAPACITY's role): slot-table size for the steady-state
    # bitvector fast path, client-side AND server-side.  0 disables (every
    # cycle does full metadata negotiation).  Runtime-tunable via autotune.
    response_cache_capacity: int = 2048

    # Pipelined data plane (HOROVOD_PIPELINE_CHUNK / HOROVOD_MAX_INFLIGHT).
    # pipeline_chunk_bytes splits each fused reduction buffer into chunks so
    # the cast-down → reduce → cast-up stages overlap across chunks inside
    # the jitted program; 0 (default) = one chunk per fused batch, i.e. the
    # batch-sized single collective (fused batches already split at the
    # fusion threshold).  max_inflight bounds the dispatched-but-unsettled
    # window in multi-process mode: >1 lets the cycle thread negotiate
    # round N+1 while the device executes round N.  Both are autotune
    # coordinates when a controller exists.
    pipeline_chunk_bytes: int = 0
    max_inflight: int = 2

    # Small-message latency war (ISSUE 8, docs/performance.md "Latency
    # fast lane").  fast_lane_threshold_bytes: ungrouped allreduces below
    # this many bytes skip the fusion-buffer batching entirely — direct
    # single-tensor dispatch through a persistent pre-compiled program
    # (still negotiated, still response-cache-slotted, bitwise-identical
    # results); 0 = off.  partition_threshold_bytes: tensors above this
    # many bytes split into priority-inheriting sub-tensors so a small
    # high-priority gradient preempts a huge transfer between parts
    # instead of queueing behind the whole of it (ByteScheduler, Peng et
    # al. SOSP 2019); reassembled transparently at synchronize; 0 = off.
    # Both must be identical on every rank (the launcher forwards them;
    # autotune broadcasts fast-lane moves).
    fast_lane_threshold_bytes: int = 0
    partition_threshold_bytes: int = 0

    # Cross-rank telemetry & health subsystem (horovod_tpu.monitor,
    # docs/monitoring.md).  HOROVOD_MONITOR=1 enables the per-rank metric
    # registry + the coordinator monitor side-channel (protocol v3);
    # HOROVOD_MONITOR_PORT > 0 additionally serves /metrics (Prometheus) +
    # /health (JSON) over HTTP on rank 0; HOROVOD_MONITOR_INTERVAL is the
    # snapshot reporting period in seconds.
    monitor: bool = False
    monitor_port: int = 0
    monitor_interval_s: float = 5.0

    # Control-plane fault tolerance (protocol v4, docs/fault_tolerance.md).
    # round_timeout_s: per-negotiation-round wall-clock deadline — the
    # server declares ranks that miss it dead and broadcasts a typed ABORT
    # to survivors; the client bounds its own response wait at 2x.  Must
    # exceed the worst legitimate inter-rank skew (XLA compiles!); 0
    # disables the deadlines (dead-socket detection is always on).
    # connect_retries / connect_backoff_ms: bounded controller-connect
    # retries with exponential backoff + jitter, so workers may start
    # before the coordinator.
    round_timeout_s: float = 0.0
    connect_retries: int = 3
    connect_backoff_ms: float = 500.0

    # Zero-RTT warm control plane (protocol v7, docs/performance.md
    # "Zero-RTT warm path").  spec_ready_after (HOROVOD_SPEC_READY_AFTER):
    # after a response-cache slot has been ready-on-first-announce for
    # this many consecutive rounds, the root piggybacks a predicted
    # next-round verdict and clients may dispatch it without waiting for
    # the response; 0 (default) = off, every round lock-step.
    # round_pipeline (HOROVOD_ROUND_PIPELINE): client-side in-flight
    # negotiation-round window — 1 (default) = lock-step, >1 sends round
    # N+1's request before round N's response is read.  Both runtime-
    # tunable (autotune coordinates in multi-process mode); results are
    # bitwise-identical either way (a mispredict only delays a verdict by
    # one normal round).
    spec_ready_after: int = 0
    round_pipeline: int = 1

    timeline_filename: str = ""
    timeline_mark_cycles: bool = False

    # Distributed collective tracing (horovod_tpu.trace, docs/timeline.md).
    # HOROVOD_TRACE=<path> arms per-tensor lifecycle spans AND writes this
    # rank's trace file there (the launcher suffixes the base per rank;
    # merge with `python -m horovod_tpu.trace`); HOROVOD_TRACE=1 arms the
    # in-memory recorder only (digests still ride the monitor side-channel,
    # bench reads the phase breakdown).  Unset = strictly zero cost.
    # HOROVOD_TRACE_RING bounds the preallocated span ring.
    trace: bool = False
    trace_filename: str = ""
    trace_ring: int = 4096

    stall_check_time_s: float = 60.0
    stall_shutdown_time_s: float = 0.0
    stall_check_disable: bool = False

    hierarchical_allreduce: bool = False
    hierarchical_allgather: bool = False
    # Two-level broadcast on the same slice topology (ISSUE 19 satellite):
    # leader exchange across DCN, then intra-slice fan-out over ICI —
    # bitwise-identical to flat (pure data movement).  Like the allgather
    # knob, the decision is purely topological (no payload crossover) and
    # rides the fusion key only, never the negotiation digest.
    hierarchical_broadcast: bool = False
    # Local-axis extent for the two-level (cross x local) collectives; 0 =
    # derive from the topology's per-process device counts (multi-host).
    hierarchical_local_size: int = 0
    # Payload crossover for the two-level data plane (ISSUE 17,
    # docs/performance.md "Hierarchical collectives"): fused allreduce
    # batches whose per-rank payload is at least this many bytes take the
    # RS(ICI) -> AR(DCN) -> AG(ICI) schedule; smaller batches stay flat
    # (the two extra phase latencies outweigh the DCN byte savings for
    # small payloads).  0 = every eligible batch goes two-level once the
    # mode is armed.  An autotune coordinate (``hier_threshold``) when the
    # mode is armed; like HOROVOD_PIPELINE_CHUNK it is NOT part of the
    # negotiation digest, so retunes cost zero control-plane traffic.
    hier_threshold_bytes: int = 0
    # Explicit slice membership for CPU/simulated worlds ("4" = uniform
    # slice size, "4,4" = per-slice sizes); empty = derive from device
    # slice_index attributes / hierarchical_local_size / process counts
    # (parallel/topology.py precedence order).
    slice_map: str = ""

    # Two-level control plane (protocol v5, docs/performance.md "Control
    # plane at scale").  HOROVOD_HIERARCHICAL_CONTROLLER=1: every rank's
    # negotiation client connects to a per-host agent
    # (common/host_agent.py, owned by the local_rank-0 process) instead of
    # the rank-0 root server; the agent collapses its host's warm-path
    # bitvector frames into ONE fixed-size uplink per round, so root-side
    # gather work scales with hosts, not ranks.  Per-rank wire bytes are
    # unchanged (frame-guarded).  Flat single-server mode remains the
    # default.  Elastic worlds compose (ISSUE 12): the agent object
    # survives re-rendezvous generations on a stable per-host port the
    # elastic driver allocates and ships through the rendezvous
    # assignment.  HOROVOD_AGENT_PORT: the agent's listen port on each
    # host (the launcher — or the elastic rendezvous — assigns one per
    # host); 0 = derive deterministically from controller port +
    # cross_rank.
    hierarchical_controller: bool = False
    agent_port: int = 0

    # Preemption-driven drains (ISSUE 12, docs/elastic.md).  When the
    # discovery source posts a preemption notice for a host (e.g.
    # TPUMetadataDiscovery's `preempted-workers` attribute), the elastic
    # driver cordons the host and DRAINs its workers — requesting a state
    # commit first (checkpoint pacing), then the clean-LEAVE departure —
    # instead of waiting for the hardware to vanish and crash the fleet
    # mid-collective.  HOROVOD_PREEMPT_GRACE_S bounds the drain: a worker
    # that has not exited by the deadline is terminated (the legacy sever
    # path), still classified as a departure, never a blacklist.
    preempt_grace_s: float = 30.0

    # Resilient state plane (ISSUE 14, docs/fault_tolerance.md "Resilient
    # state plane").  HOROVOD_CKPT_DIR arms overlap-scheduled sharded
    # checkpoints: on every elastic-state commit each rank streams its
    # 1/N shard of the serialized state through the engine's lowest-
    # priority `checkpoint` dispatch lane (two-phase manifest; gradient
    # dispatch order provably unchanged) and serves the committed epoch
    # to re-joining ranks peer-to-peer (disk is the fallback).
    # HOROVOD_CKPT_CHUNK bounds one lane item's write; HOROVOD_CKPT_
    # LANE_BUDGET bounds chunks per engine cycle.  HOROVOD_COMMIT_MAX_
    # AGE_S is the autoscaler's stale-state guard: evict/scale_in
    # decisions are refused while the fleet's last commit is older than
    # this (0 = off) — shrinking a world whose restore point is stale
    # would convert an orderly drain into lost work.
    ckpt_dir: str = ""
    ckpt_chunk_bytes: int = 1 << 20
    ckpt_lane_budget: int = 2
    commit_max_age_s: float = 0.0

    # ZeRO-sharded optimizer (ISSUE 15, docs/performance.md "Sharded
    # optimizer (ZeRO)").  HOROVOD_SHARDED_OPTIMIZER=1 flips every
    # DistributedOptimizer built without an explicit ``sharded=`` to the
    # reduce-scatter → 1/N shard update → allgather data plane: optimizer
    # state lives 1/world per rank in HBM and gradient bytes ride the
    # scatter at half an allreduce's wire cost.  Must be identical on
    # every rank (the launcher's --sharded forwards it): the sharded flag
    # is part of the negotiation digest, so divergence fails fast.
    sharded_optimizer: bool = False

    # Full parameter sharding (ISSUE 18, ZeRO-3/FSDP — docs/performance.md
    # "Full parameter sharding (FSDP)").  HOROVOD_SHARDED_PARAMS=1 flips
    # every DistributedOptimizer built without an explicit ``sharded=`` to
    # ``sharded="full"``: parameters live 1/world per rank, forward-pass
    # parameters rematerialize through prefetch allgathers on the engine's
    # PREFETCH lane, gradients reduce-scatter straight into the owning
    # shard.  Takes precedence over HOROVOD_SHARDED_OPTIMIZER; must be
    # identical on every rank (part of the negotiation digest as the
    # "sharded-full" token).  HOROVOD_PREFETCH_DEPTH bounds how many
    # buckets of gathered parameters may be in flight ahead of
    # consumption (peak HBM = shard + depth × bucket bytes); a local
    # knob like HOROVOD_PIPELINE_CHUNK — never negotiated, autotunable.
    sharded_params: bool = False
    prefetch_depth: int = 2

    # Closed-loop elastic autoscaling (docs/elastic.md "Closed-loop
    # autoscaling") — consumed by the elastic DRIVER (torovodrun
    # --host-discovery-script), not by workers.  HOROVOD_AUTOSCALE=1
    # turns the policy loop on (requires --monitor-port so the driver can
    # poll rank 0's /health for the aggregation summary); the remaining
    # knobs parameterize elastic/autoscale.ScalePolicy: observation
    # period, scale-out queue thresholds (absolute + EWMA trend),
    # straggler-evict factor vs the peer median, hysteresis persistence
    # (consecutive observations), post-decision cooldown, and the idle
    # window before scale-in.
    autoscale: bool = False
    autoscale_interval_s: float = 5.0
    autoscale_queue_high: float = 16.0
    autoscale_queue_trend: float = 4.0
    autoscale_straggler_factor: float = 3.0
    autoscale_persistence: int = 3
    autoscale_cooldown_s: float = 30.0
    autoscale_idle_s: float = 60.0
    # Request-rate / latency-target autoscaling (ISSUE 19, serving mode;
    # docs/serving.md).  All three are off at 0.  autoscale_rate_high:
    # fleet-aggregate offered QPS per replica above which (with a rising
    # EWMA trend) the policy scales out.  autoscale_latency_target_ms:
    # serving p99 latency SLO — p99 above target counts toward scale_out
    # with the same persistence/cooldown hysteresis as the queue signals.
    # autoscale_idle_qps: offered load below this feeds the idle timer
    # (scale_in after autoscale_idle_s), replacing the training-progress
    # idle test when serving instruments are present.
    autoscale_rate_high: float = 0.0
    autoscale_latency_target_ms: float = 0.0
    autoscale_idle_qps: float = 0.0

    # Data-parallel serving plane (ISSUE 19, horovod_tpu.serve,
    # docs/serving.md).  HOROVOD_SERVE=1 turns a launched worker fleet
    # into inference replicas (torovodrun --serve); HOROVOD_SERVE_PORT is
    # the rank-0 front-door HTTP ingest port (0 = in-process API only).
    # serve_max_batch bounds one forward step's batch; serve_buckets
    # ("1,2,4,8") pins the padded batch shapes the jitted forward may
    # see — batch-size churn rounds up to a bucket so the program cache
    # never recompiles mid-traffic (empty = powers of two up to
    # serve_max_batch).  serve_deadline_ms is the per-request admission
    # deadline (expired requests are failed, never dispatched);
    # serve_max_inflight bounds admitted-but-unsettled batches (the
    # HOROVOD_MAX_INFLIGHT window semantics applied at the front door;
    # 0 = inherit max_inflight); serve_queue_depth bounds the ingest
    # queue — a full queue is backpressure (HTTP 429 + queue-depth
    # signal), the load-balancer/autoscaler signal to shed or grow.
    serve: bool = False
    serve_port: int = 0
    serve_max_batch: int = 8
    serve_buckets: str = ""
    serve_deadline_ms: float = 1000.0
    serve_max_inflight: int = 0
    serve_queue_depth: int = 128
    # Serving fault tolerance (ISSUE 20) — ALL serve-local: consumed by
    # the front door / batcher on this rank only, never negotiated, zero
    # bytes on the warm control-plane frame.  serve_retries bounds the
    # front door's deadline-charged retry loop for RETRYABLE failures;
    # serve_hedge_ms > 0 arms tail-latency hedging (the value is the
    # cold-start delay until an observed p99 exists); the breaker trips
    # after serve_breaker_threshold consecutive retryable failures,
    # fast-fails 503 + Retry-After for serve_breaker_reset_s, then
    # half-opens and closes after serve_breaker_probes good probes;
    # serve_quarantine_after consecutive forward failures of ONE request
    # fail it terminally (poisoned input, not replica fault).
    serve_retries: int = 2
    serve_hedge_ms: float = 0.0
    serve_breaker_threshold: int = 5
    serve_breaker_reset_s: float = 5.0
    serve_breaker_probes: int = 2
    serve_quarantine_after: int = 3

    autotune: bool = False
    autotune_log: str = ""
    autotune_warmup_samples: int = 3
    autotune_steps_per_sample: int = 10
    autotune_max_evals: int = 48

    log_level: str = "warning"
    batch_d2d_memcopies: bool = True

    num_collective_streams: int = 1
    donate_fusion_buffers: bool = True
    mesh_axis_name: str = "hvd"
    # Run the coordinator cycle inline on the submitting thread for blocking
    # single-controller ops (HOROVOD_INLINE_KICK; the small-tensor latency
    # fast path — off = legacy wake-the-cycle-thread dispatch).
    inline_kick: bool = True
    # Pod mode (HOROVOD_ONE_PROC_PER_HOST): one launched process drives all
    # of its host's chips.  jax.distributed auto-detects the world, and
    # rank()/local_rank()/local_size() come from the device topology — the
    # launcher's env values describe the PROCESS world (control plane),
    # not the device world.
    one_proc_per_host: bool = False

    # Control plane (multi-process mode). Set by the launcher.
    controller_addr: str = ""
    controller_port: int = 0
    controller_port2: int = 0
    rank_env: int = -1
    size_env: int = -1
    local_rank_env: int = -1
    local_size_env: int = -1
    cross_rank_env: int = -1
    cross_size_env: int = -1

    # Elastic
    elastic: bool = False

    @classmethod
    def from_env(cls) -> "Config":
        cfg = cls(
            fusion_threshold_bytes=_env_int("FUSION_THRESHOLD", 64 * 1024 * 1024),
            cycle_time_ms=_env_float("CYCLE_TIME", 1.0),
            cache_capacity=_env_int("CACHE_CAPACITY", 1024),
            response_cache_capacity=_env_int("RESPONSE_CACHE_CAPACITY", 2048),
            pipeline_chunk_bytes=_env_int("PIPELINE_CHUNK", 0),
            max_inflight=_env_int("MAX_INFLIGHT", 2),
            fast_lane_threshold_bytes=_env_int("FAST_LANE_THRESHOLD", 0),
            partition_threshold_bytes=_env_int("PARTITION_THRESHOLD", 0),
            monitor=_env_bool("MONITOR", False),
            monitor_port=_env_int("MONITOR_PORT", 0),
            monitor_interval_s=_env_float("MONITOR_INTERVAL", 5.0),
            round_timeout_s=_env_float("ROUND_TIMEOUT_S", 0.0),
            connect_retries=_env_int("CONNECT_RETRIES", 3),
            connect_backoff_ms=_env_float("CONNECT_BACKOFF_MS", 500.0),
            spec_ready_after=_env_int("SPEC_READY_AFTER", 0),
            round_pipeline=_env_int("ROUND_PIPELINE", 1),
            timeline_filename=_env("TIMELINE", "") or "",
            timeline_mark_cycles=_env_bool("TIMELINE_MARK_CYCLES", False),
            trace_ring=_env_int("TRACE_RING", 4096),
            stall_check_time_s=_env_float("STALL_CHECK_TIME", 60.0),
            stall_shutdown_time_s=_env_float("STALL_SHUTDOWN_TIME", 0.0),
            stall_check_disable=_env_bool("STALL_CHECK_DISABLE", False),
            hierarchical_allreduce=_env_bool("HIERARCHICAL_ALLREDUCE", False),
            hierarchical_allgather=_env_bool("HIERARCHICAL_ALLGATHER", False),
            hierarchical_broadcast=_env_bool("HIERARCHICAL_BROADCAST", False),
            hierarchical_local_size=_env_int("HIERARCHICAL_LOCAL_SIZE", 0),
            hier_threshold_bytes=_env_int("HIER_THRESHOLD", 0),
            slice_map=_env("SLICE_MAP", "") or "",
            hierarchical_controller=_env_bool("HIERARCHICAL_CONTROLLER",
                                              False),
            agent_port=_env_int("AGENT_PORT", 0),
            preempt_grace_s=_env_float("PREEMPT_GRACE_S", 30.0),
            ckpt_dir=_env("CKPT_DIR", "") or "",
            ckpt_chunk_bytes=_env_int("CKPT_CHUNK", 1 << 20),
            ckpt_lane_budget=_env_int("CKPT_LANE_BUDGET", 2),
            commit_max_age_s=_env_float("COMMIT_MAX_AGE_S", 0.0),
            sharded_optimizer=_env_bool("SHARDED_OPTIMIZER", False),
            sharded_params=_env_bool("SHARDED_PARAMS", False),
            prefetch_depth=_env_int("PREFETCH_DEPTH", 2),
            autoscale=_env_bool("AUTOSCALE", False),
            autoscale_interval_s=_env_float("AUTOSCALE_INTERVAL", 5.0),
            autoscale_queue_high=_env_float("AUTOSCALE_QUEUE_HIGH", 16.0),
            autoscale_queue_trend=_env_float("AUTOSCALE_QUEUE_TREND", 4.0),
            autoscale_straggler_factor=_env_float(
                "AUTOSCALE_STRAGGLER_FACTOR", 3.0),
            autoscale_persistence=_env_int("AUTOSCALE_PERSISTENCE", 3),
            autoscale_cooldown_s=_env_float("AUTOSCALE_COOLDOWN", 30.0),
            autoscale_idle_s=_env_float("AUTOSCALE_IDLE_S", 60.0),
            autoscale_rate_high=_env_float("AUTOSCALE_RATE_HIGH", 0.0),
            autoscale_latency_target_ms=_env_float(
                "AUTOSCALE_LATENCY_TARGET_MS", 0.0),
            autoscale_idle_qps=_env_float("AUTOSCALE_IDLE_QPS", 0.0),
            serve=_env_bool("SERVE", False),
            serve_port=_env_int("SERVE_PORT", 0),
            serve_max_batch=_env_int("SERVE_MAX_BATCH", 8),
            serve_buckets=_env("SERVE_BUCKETS", "") or "",
            serve_deadline_ms=_env_float("SERVE_DEADLINE_MS", 1000.0),
            serve_max_inflight=_env_int("SERVE_MAX_INFLIGHT", 0),
            serve_queue_depth=_env_int("SERVE_QUEUE_DEPTH", 128),
            serve_retries=_env_int("SERVE_RETRIES", 2),
            serve_hedge_ms=_env_float("SERVE_HEDGE_MS", 0.0),
            serve_breaker_threshold=_env_int("SERVE_BREAKER_THRESHOLD", 5),
            serve_breaker_reset_s=_env_float("SERVE_BREAKER_RESET_S", 5.0),
            serve_breaker_probes=_env_int("SERVE_BREAKER_PROBES", 2),
            serve_quarantine_after=_env_int("SERVE_QUARANTINE_AFTER", 3),
            autotune=_env_bool("AUTOTUNE", False),
            autotune_log=_env("AUTOTUNE_LOG", "") or "",
            autotune_warmup_samples=_env_int("AUTOTUNE_WARMUP_SAMPLES", 3),
            autotune_steps_per_sample=_env_int("AUTOTUNE_STEPS_PER_SAMPLE", 10),
            autotune_max_evals=_env_int("AUTOTUNE_MAX_EVALS", 48),
            log_level=(_env("LOG_LEVEL", "warning") or "warning").lower(),
            batch_d2d_memcopies=_env_bool("BATCH_D2D_MEMCOPIES", True),
            num_collective_streams=_env_int("NUM_STREAMS", 1),
            donate_fusion_buffers=_env_bool("DONATE_FUSION_BUFFERS", True),
            inline_kick=_env_bool("INLINE_KICK", True),
            one_proc_per_host=_env_bool("ONE_PROC_PER_HOST", False),
            controller_addr=_env("CONTROLLER_ADDR", "") or "",
            controller_port=_env_int("CONTROLLER_PORT", 0),
            controller_port2=_env_int("CONTROLLER_PORT2", 0),
            rank_env=_env_int("RANK", -1),
            size_env=_env_int("SIZE", -1),
            local_rank_env=_env_int("LOCAL_RANK", -1),
            local_size_env=_env_int("LOCAL_SIZE", -1),
            cross_rank_env=_env_int("CROSS_RANK", -1),
            cross_size_env=_env_int("CROSS_SIZE", -1),
            elastic=_env_bool("ELASTIC", False),
        )
        if _env_int("CACHE_CAPACITY", 1024) == 0:
            cfg.cache_enabled = False
        # HOROVOD_TRACE: a bool-ish value arms the in-memory recorder only;
        # anything else is the per-rank trace file path (and arms it).
        raw_trace = (_env("TRACE", "") or "").strip()
        if raw_trace:
            cfg.trace = raw_trace.lower() not in ("0", "false", "no", "off")
            if cfg.trace and raw_trace.lower() not in ("1", "true", "yes",
                                                       "on"):
                cfg.trace_filename = raw_trace
        return cfg
