"""Shared port-selection helpers for launchers/executors.

Two distinct problems, two helpers:

- ``free_ports(n)``: ports free on THIS machine (bind-probed, SO_REUSEADDR,
  all probes held open so one call can't return duplicates).  Only valid
  when the service will bind on this same machine.
- ``remote_ports(n, seed)``: ports for a service that binds on a DIFFERENT
  host, where bind-probing here proves nothing.  Picks from a high range,
  deterministically from ``seed`` so (a) every participant that knows the
  seed computes the same ports with no extra messages and (b) a retry with
  a new seed moves to fresh ports after a collision.
"""

from __future__ import annotations

import random
import socket
from typing import List


def free_ports(n: int) -> List[int]:
    socks = []
    try:
        for _ in range(n):
            s = socket.socket()
            s.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
            s.bind(("", 0))
            socks.append(s)
        return [s.getsockname()[1] for s in socks]
    finally:
        for s in socks:
            s.close()


def remote_ports(n: int, seed: int) -> List[int]:
    rng = random.Random(seed)
    base = rng.randrange(20000, 60000 - n)
    return [base + i for i in range(n)]


def is_local_host(hostname: str) -> bool:
    return hostname in ("localhost", "127.0.0.1", socket.gethostname())
