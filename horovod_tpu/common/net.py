"""Shared port-selection helpers for launchers/executors.

Two distinct problems, two helpers:

- ``free_ports(n)``: ports free on THIS machine (bind-probed, SO_REUSEADDR,
  all probes held open so one call can't return duplicates).  Only valid
  when the service will bind on this same machine.
- ``remote_ports(n, seed)``: ports for a service that binds on a DIFFERENT
  host, where bind-probing here proves nothing.  Picks from a high range,
  deterministically from ``seed`` so (a) every participant that knows the
  seed computes the same ports with no extra messages and (b) a retry with
  a new seed moves to fresh ports after a collision.
"""

from __future__ import annotations

import functools
import random
import socket
import time
from typing import Callable, List, Optional, Tuple, Type


def retry_with_backoff(fn: Callable, retries: int = 3,
                       base_ms: float = 200.0, max_ms: float = 5000.0,
                       jitter: float = 0.25,
                       exceptions: Tuple[Type[BaseException], ...] = (OSError,),
                       on_retry: Optional[Callable] = None):
    """Call ``fn()``; on a listed exception sleep ``base_ms * 2**attempt``
    (capped at ``max_ms``, ± ``jitter`` fraction of randomization so a
    fleet of workers retrying the same dead endpoint doesn't stampede in
    lock-step) and try again, up to ``retries`` retries.  The final
    failure re-raises the last exception.

    ``on_retry(attempt, exc, delay_s)`` — optional observer, called before
    each sleep (loggers; tests assert schedules through it).

    Shared by the controller's connect path (workers may start before the
    coordinator) and the elastic driver's worker-notification path (a
    transiently unreachable worker must still learn about host changes).
    """
    attempt = 0
    while True:
        try:
            return fn()
        except exceptions as exc:
            if attempt >= max(0, int(retries)):
                raise
            delay_s = min(max_ms, base_ms * (2 ** attempt)) / 1000.0
            delay_s *= 1.0 + random.uniform(-jitter, jitter)
            delay_s = max(0.0, delay_s)
            if on_retry is not None:
                on_retry(attempt, exc, delay_s)
            time.sleep(delay_s)
            attempt += 1


def free_ports(n: int) -> List[int]:
    socks = []
    try:
        for _ in range(n):
            s = socket.socket()
            s.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
            s.bind(("", 0))
            socks.append(s)
        return [s.getsockname()[1] for s in socks]
    finally:
        for s in socks:
            s.close()


def remote_ports(n: int, seed: int) -> List[int]:
    rng = random.Random(seed)
    base = rng.randrange(20000, 60000 - n)
    return [base + i for i in range(n)]


def routable_addr() -> str:
    """An address REMOTE hosts can reach this machine at (for rendezvous /
    controller endpoints): the primary outbound interface's address, or the
    FQDN when that cannot be determined.  The UDP connect sends no packets —
    it only makes the kernel pick a source address."""
    s = socket.socket(socket.AF_INET, socket.SOCK_DGRAM)
    try:
        s.connect(("8.8.8.8", 80))
        return s.getsockname()[0]
    except OSError:
        return socket.getfqdn()
    finally:
        s.close()


@functools.lru_cache(maxsize=1)
def _local_identity():
    """This machine's names + resolved addresses, computed once (DNS can
    block seconds per lookup; callers sit in polling loops)."""
    local_names = {socket.gethostname(), socket.getfqdn()}
    local_addrs = set()
    for n in local_names:
        try:
            local_addrs.update(socket.gethostbyname_ex(n)[2])
        except OSError:
            pass
    try:
        local_addrs.add(routable_addr())
    except OSError:
        pass
    return local_names, local_addrs


# Only SUCCESSFUL resolutions are cached: a transient DNS failure must be
# retried on the next call, not frozen as "remote" for the process lifetime
# (which would send the bootstrap ssh-ing to itself / picking blind remote
# ports for a local coordinator).
_is_local_cache: dict = {}


def is_local_host(hostname: str) -> bool:
    """True when ``hostname`` refers to this machine — by name, FQDN,
    alias, or any resolved address of either — so local coordinators named
    by FQDN/IP still get bind-probed ports instead of blind remote ones.
    Cached on success only: resolution can block on slow DNS and callers
    poll, but a failed lookup is transient and must not stick."""
    if hostname in ("localhost", "127.0.0.1", "::1"):
        return True
    cached = _is_local_cache.get(hostname)
    if cached is not None:
        return cached
    local_names, local_addrs = _local_identity()
    if hostname in local_names:
        _is_local_cache[hostname] = True
        return True
    try:
        target_addrs = set(socket.gethostbyname_ex(hostname)[2])
    except OSError:
        return False
    result = (any(a.startswith("127.") for a in target_addrs)
              or bool(target_addrs & local_addrs))
    _is_local_cache[hostname] = result
    return result
