"""Per-host control-plane aggregator (protocol v5, jax-free).

The scale-out half of the hierarchical control plane (docs/performance.md
"Control plane at scale"): one ``HostAgent`` per host sits between its
local ranks' :class:`~.controller.TCPController` clients and the rank-0
coordinator (``csrc/coordinator.cc``).  Local ranks connect to the agent
exactly as they would to the root — same handshake, byte-identical frames,
so the per-rank warm path stays the guarded ~13 B/cycle — while the agent
presents the whole host to the root as ONE connection:

- **uplink**: each round the agent collects one frame from every local
  rank.  In the synchronized warm steady state (every rank sent a pure
  bitvector frame with identical bits — the common case, since all ranks
  submit the same tensors in the same cycle) the frames collapse into one
  fixed-size aggregate section that counts for every local rank at once;
  anything else (full announces, sanitizer tags, FLT1 ads, join frames,
  asymmetric rounds) is forwarded per-rank, byte-identical, so flat-mode
  semantics survive unchanged.  MON1 telemetry blobs are extracted and
  deduplicated into one uplink section per round instead of riding N
  store-and-forward frames.
- **downlink**: the root's response is already rank-agnostic (the flat
  server broadcasts one identical frame to every rank), so the agent fans
  it down verbatim.  Typed ABORT frames are fanned down the same way.
- **liveness**: a local rank whose socket dies is propagated up in the
  next uplink's dead-rank section, so the root aborts the fleet with exact
  rank attribution; the agent's own death severs its root connection, and
  the root declares the whole host's ranks dead (coarse but correct —
  the agent was those ranks' only path).
- **clean LEAVE (protocol v6)**: a local rank announcing its own orderly
  departure sends the typed LEAVE frame in place of a round frame; the
  agent forwards it upstream verbatim (the root drops the rank with no
  verdict) and then retires the rank — the host's uplink SHRINKS to the
  survivors and the aggregate warm path re-engages over the smaller rank
  set, instead of the departure killing the whole host's connection.

Root-side gather work therefore scales with hosts, not ranks: one
readable fd, one frame parse and one response write per host per round.

**Generation survival (ISSUE 12):** the agent's identity is its HOST, not
a rendezvous generation.  ``end_generation``/``new_generation`` tear down
and re-form the per-generation connections (upstream root, local rank
sockets, round thread) while the listening socket — on the stable
per-host port the elastic driver allocated — stays bound, so the same
agent object serves consecutive re-rendezvous generations whose rank sets
grew, shrank or were renumbered.  This is what lets
``HOROVOD_HIERARCHICAL_CONTROLLER=1`` compose with elastic worlds instead
of being silently forced flat.

No jax imports: the agent must run on the jax-free fast test tier and in
launcher-adjacent processes.
"""

from __future__ import annotations

import selectors
import socket
import struct
import threading
import time
from typing import Dict, List, Optional, Tuple

from ..utils.logging import get_logger

log = get_logger()

# Wire constants — must match csrc/coordinator.cc.
_AGENT_HELLO = 0xFFFFFF05
_HUP_MAGIC = 0x35505548        # "HUP5"
_MON_MAGIC = 0x314E4F4D        # "MON1"
_ABORT_ESCAPE = 0xFFFFFFFF
# Clean-LEAVE frame (protocol v6): escape word + "LVE6" magic.
_LEAVE_ESCAPE = 0xFFFFFFFE
_LVE_MAGIC = 0x3645564C
# Zero-RTT warm path (protocol v7): a speculating rank's warm frame is
# the 13-byte core plus a one-byte ZRT7 confirm section.  Identical
# confirms across the host stay on the fixed-size aggregate uplink path.
_ZRT_MAGIC = 0x3754525A


def _is_leave_frame(data: bytes) -> bool:
    return (len(data) >= 8
            and struct.unpack_from("<II", data) == (_LEAVE_ESCAPE,
                                                    _LVE_MAGIC))


def _read_exact(sock: socket.socket, n: int,
                stop: Optional[threading.Event] = None) -> Optional[bytes]:
    """Blocking exact read with stop-aware short timeouts; None on EOF or
    stop."""
    buf = b""
    while len(buf) < n:
        if stop is not None and stop.is_set():
            return None
        try:
            chunk = sock.recv(n - len(buf))
        except socket.timeout:
            continue
        except OSError:
            return None
        if not chunk:
            return None
        buf += chunk
    return buf


def _read_frame(sock: socket.socket,
                stop: Optional[threading.Event] = None) -> Optional[bytes]:
    hdr = _read_exact(sock, 4, stop)
    if hdr is None:
        return None
    (ln,) = struct.unpack("<I", hdr)
    if ln == 0:
        return b""
    return _read_exact(sock, ln, stop)


def _write_frame(sock: socket.socket, payload: bytes) -> bool:
    try:
        sock.sendall(struct.pack("<I", len(payload)) + payload)
        return True
    except OSError:
        return False


def split_rank_frame(data: bytes):
    """Parse a client request frame into ``(n_announce, n_tag, core_end,
    trailing)`` where ``trailing`` is the ``[(magic, payload)]`` list of
    generic trailing sections and ``core_end`` is the offset where they
    begin.  Returns None when the frame does not parse — the caller then
    forwards it verbatim (never aggregates), so a framing bug degrades to
    flat-mode behavior instead of corruption."""
    try:
        off = 0
        (n_ann,) = struct.unpack_from("<I", data, off)
        off += 4
        for _ in range(n_ann):
            off += 2                                  # required
            for _f in range(5):                       # name/digest/group/
                (ln,) = struct.unpack_from("<H", data, off)   # datadep/tag
                off += 2 + ln
        (bv_len,) = struct.unpack_from("<I", data, off)
        off += 4 + bv_len
        (n_tag,) = struct.unpack_from("<I", data, off)
        off += 4
        for _ in range(n_tag):
            (_slot, ln) = struct.unpack_from("<IH", data, off)
            off += 6 + ln
        core_end = off
        trailing = []
        while off + 8 <= len(data):
            magic, ln = struct.unpack_from("<II", data, off)
            off += 8
            if off + ln > len(data):
                return None
            trailing.append((magic, data[off:off + ln]))
            off += ln
        if off != len(data):
            return None
        return n_ann, n_tag, core_end, trailing
    except struct.error:
        return None


class AgentStats:
    """Uplink accounting the frame-guard tests pin: exactly one uplink per
    round, and how often the fixed-size aggregate path engaged.
    Cumulative across re-rendezvous GENERATIONS (ISSUE 12): the agent is
    keyed on its host, not on a generation, so the counters survive
    ``new_generation`` — ``generations`` records how many worlds this one
    agent object has served."""

    def __init__(self):
        self.rounds = 0
        self.uplink_frames = 0
        self.uplink_bytes = 0
        self.agg_rounds = 0            # rounds collapsed to ONE aggregate
        self.last_agg_uplink_len = 0   # payload bytes of the last aggregate
        self.subframes_forwarded = 0   # per-rank pass-through frames
        self.mon_blobs_forwarded = 0   # MON1 blobs deduped into uplinks
        self.responses_fanned = 0
        self.dead_reports = 0          # out-of-round dead-rank uplinks
        self.leaves_forwarded = 0      # clean LEAVEs relayed upstream (v6)
        self.generations = 0           # worlds served by this agent object


class HostAgent:
    """One per-host aggregation point between local ranks and the root."""

    def __init__(self, port: int, upstream_addr: str, upstream_port: int,
                 ranks: List[int], host_index: int = 0,
                 listen_addr: str = "127.0.0.1",
                 connect_timeout_ms: int = 60000):
        if not ranks:
            raise ValueError("HostAgent needs at least one local rank")
        self.ranks = sorted(int(r) for r in ranks)
        self.host_index = int(host_index)
        self.upstream_addr = upstream_addr
        self.upstream_port = int(upstream_port)
        self.connect_timeout_ms = int(connect_timeout_ms)
        self.stats = AgentStats()
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self._up: Optional[socket.socket] = None
        self._local: Dict[int, socket.socket] = {}   # rank -> socket
        self._reported_dead: set = set()
        # Ranks whose EOF arrived AFTER their round frame was already in
        # hand: reported upstream once the completed round's uplink (which
        # legitimately includes their last announce) has gone out.
        self._deferred_dead: List[int] = []
        # Ranks whose round frame was a clean LEAVE (protocol v6): the
        # frame is forwarded upstream as a verbatim subframe, and the rank
        # is retired — removed from the local set and from ``ranks`` so
        # the aggregate warm path re-engages over the SHRUNK host — once
        # the round's response has been fanned to the survivors.  Their
        # trailing EOF must never become a dead-rank report.
        self._left_pending: set = set()
        # Per-rank reassembly buffers, persistent ACROSS rounds: a
        # speculating or pipelined rank (protocol v7) legitimately sends
        # round N+1's frame before round N's response has been fanned
        # down, so bytes beyond the current round's frame must survive
        # the gather instead of dying with a per-call buffer.
        self._bufs: Dict[int, bytes] = {}
        self.error: Optional[str] = None
        # Bound before start() returns so callers (and port-0 users) know
        # where local ranks must connect.
        self._lsock = socket.socket()
        self._lsock.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self._lsock.bind((listen_addr, int(port)))
        self._lsock.listen(len(self.ranks))
        self._lsock.settimeout(0.2)
        self.port = self._lsock.getsockname()[1]

    # ------------------------------------------------------------ lifecycle
    def start(self) -> "HostAgent":
        self.stats.generations += 1
        self._thread = threading.Thread(target=self._run, daemon=True,
                                        name=f"hvd-host-agent-{self.host_index}")
        self._thread.start()
        return self

    def end_generation(self) -> None:
        """Tear down this GENERATION's connections — upstream root, local
        rank sockets, the round thread — while keeping the LISTENER bound
        (ISSUE 12): the agent's identity is its host (and the stable port
        the elastic driver allocated for that host), not a generation.
        ``new_generation`` re-accepts the next world on the same port.
        Idempotent; safe on a generation that already failed."""
        self._stop.set()
        for s in [self._up, *self._local.values()]:
            if s is not None:
                try:
                    s.shutdown(socket.SHUT_RDWR)
                except OSError:
                    pass
        t = self._thread
        if t is not None:
            t.join(timeout=10)
            if t.is_alive():
                # Left in place as poison: new_generation refuses to run
                # beside a thread that would read the replaced stop event
                # and race the fresh generation's state.
                self.error = (self.error
                              or "generation thread failed to stop")
            else:
                self._thread = None
        for s in [self._up, *self._local.values()]:
            if s is not None:
                try:
                    s.close()
                except OSError:
                    pass
        self._local.clear()
        self._up = None
        self._bufs.clear()
        self._left_pending.clear()
        self._reported_dead.clear()
        self._deferred_dead = []

    def new_generation(self, upstream_addr: str, upstream_port: int,
                       ranks: List[int],
                       host_index: Optional[int] = None) -> "HostAgent":
        """Serve the NEXT re-rendezvous generation from the same agent
        object: the previous generation (if any) is ended, the rank set —
        which may have grown, shrunk, or been renumbered by the elastic
        driver — replaces the old one, the uplink re-connects to the new
        generation's root, and local ranks re-connect to the SAME listen
        port.  This is what lets ``HOROVOD_HIERARCHICAL_CONTROLLER=1``
        survive elastic churn: LEAVE/join re-negotiate the host's uplink
        width instead of forcing the fleet flat."""
        if not ranks:
            raise ValueError("HostAgent.new_generation needs ranks")
        self.end_generation()
        if self._thread is not None and self._thread.is_alive():
            # The old round thread would read the REPLACED stop event and
            # run concurrently with the new generation's thread, racing
            # on the cleared per-generation state — refuse loudly; the
            # caller falls back to a fresh agent on a fresh port.
            raise RuntimeError(
                "host agent: the previous generation's thread failed to "
                "stop; cannot serve a new generation")
        self.ranks = sorted(int(r) for r in ranks)
        if host_index is not None:
            self.host_index = int(host_index)
        self.upstream_addr = upstream_addr
        self.upstream_port = int(upstream_port)
        self.error = None
        # A fresh stop event only after the old thread is JOINED — the old
        # thread reads self._stop, so replacing it earlier could leave it
        # running against a cleared event.
        self._stop = threading.Event()
        self._lsock.listen(len(self.ranks))
        return self.start()

    def stop(self) -> None:
        self.end_generation()
        try:
            self._lsock.close()
        except OSError:
            pass

    close = stop

    def kill(self) -> None:
        """Abrupt death for fault tests: sever every socket WITHOUT the
        orderly dead-rank reporting — the root must attribute this host's
        ranks from the severed connection alone."""
        self._stop.set()
        for s in [self._lsock, self._up, *self._local.values()]:
            if s is not None:
                try:
                    s.shutdown(socket.SHUT_RDWR)
                except OSError:
                    pass

    # ----------------------------------------------------------- bootstrap
    def _accept_local(self) -> bool:
        """Accept exactly one connection per local rank (handshake: the
        rank id, same as the root's flat handshake)."""
        deadline = time.monotonic() + self.connect_timeout_ms / 1000.0
        want = set(self.ranks)
        while want and not self._stop.is_set():
            if time.monotonic() > deadline:
                self.error = f"local ranks never connected: {sorted(want)}"
                return False
            try:
                conn, _ = self._lsock.accept()
            except socket.timeout:
                continue
            except OSError:
                return False
            conn.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
            conn.settimeout(0.2)
            hs = _read_exact(conn, 4, self._stop)
            if hs is None:
                conn.close()
                continue
            (rank,) = struct.unpack("<I", hs)
            if rank not in want:
                conn.close()
                continue
            want.discard(rank)
            self._local[rank] = conn
        return not want

    def _connect_upstream(self) -> bool:
        deadline = time.monotonic() + self.connect_timeout_ms / 1000.0
        while not self._stop.is_set():
            if time.monotonic() > deadline:
                self.error = (f"root coordinator at {self.upstream_addr}:"
                              f"{self.upstream_port} not reachable")
                return False
            try:
                s = socket.create_connection(
                    (self.upstream_addr, self.upstream_port), timeout=2.0)
            except OSError:
                time.sleep(0.05)
                continue
            s.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
            s.settimeout(0.2)
            try:
                s.sendall(struct.pack("<I", _AGENT_HELLO))
                claim = struct.pack("<II", self.host_index, len(self.ranks))
                claim += b"".join(struct.pack("<I", r) for r in self.ranks)
                if not _write_frame(s, claim):
                    raise OSError("handshake write failed")
            except OSError:
                s.close()
                time.sleep(0.05)
                continue
            self._up = s
            return True

    # ---------------------------------------------------------- round loop
    def _take_frame(self, rank: int, frames: Dict[int, bytes]) -> None:
        """Move one complete frame (if reassembled) from the rank's
        persistent buffer into this round's frame set."""
        buf = self._bufs.get(rank, b"")
        if len(buf) < 4:
            return
        (ln,) = struct.unpack_from("<I", buf)
        if len(buf) < 4 + ln:
            return
        frames[rank] = buf[4:4 + ln]
        self._bufs[rank] = buf[4 + ln:]
        if _is_leave_frame(frames[rank]):
            # Clean departure (protocol v6): the LEAVE is this rank's
            # round frame — forwarded upstream verbatim so the root drops
            # the rank — and the rank retires after the round completes.
            self._left_pending.add(rank)

    def _gather_local(self, sel) -> Optional[Dict[int, bytes]]:
        """One frame from every live local rank, multiplexed through the
        round loop's long-lived selector (registered ONCE per connection,
        like the root's poller — not rebuilt per round).  Returns None
        when the round cannot complete (death/abort/teardown) after
        handling it: local deaths are reported upstream, an upstream frame
        arriving mid-gather (an ABORT — the only unsolicited downlink) is
        fanned down.  Reassembly buffers persist across rounds: a
        speculating/pipelined rank's early next-round frame simply waits
        its turn (it satisfies the NEXT gather immediately)."""
        frames: Dict[int, bytes] = {}
        # Leftover frames from ranks that ran ahead of the fan-out.
        for rank in list(self._local):
            self._take_frame(rank, frames)
        while not self._stop.is_set():
            if all(r in frames for r in self._local):
                return frames
            try:
                events = sel.select(timeout=0.2)
            except OSError:
                return None
            for key, _ev in events:
                rank = key.data
                if rank is None:
                    # Unsolicited downlink mid-gather = a typed ABORT
                    # (or root death): fan it down and stop.
                    frame = _read_frame(self._up, self._stop)
                    if frame is not None:
                        self._fan_down(frame)
                    self._sever_local()
                    return None
                if rank not in self._local:
                    continue
                s = key.fileobj
                try:
                    chunk = s.recv(65536)
                except socket.timeout:
                    continue
                except OSError:
                    chunk = b""
                if not chunk:
                    if rank in frames or rank in self._left_pending:
                        # EOF AFTER this round's frame (a rank dying right
                        # after its send, or a leaver's expected sever):
                        # the frame in hand still counts — retire the
                        # socket now, report once the round's uplink has
                        # gone out.  A clean leaver is never reported.
                        sel.unregister(s)
                        self._local.pop(rank, None)
                        self._bufs.pop(rank, None)
                        if rank not in self._left_pending:
                            self._deferred_dead.append(rank)
                        continue
                    sel.unregister(s)
                    self._bufs.pop(rank, None)
                    self._on_local_death(rank)
                    return None
                self._bufs[rank] = self._bufs.get(rank, b"") + chunk
                if rank not in frames:
                    self._take_frame(rank, frames)
        return None

    def _on_local_death(self, rank: int) -> None:
        """A local rank's socket died: report it upstream (the root aborts
        the fleet with exact rank attribution) and relay the verdict."""
        self._local.pop(rank, None)
        self._report_dead([rank])

    def _report_dead(self, ranks: List[int]) -> None:
        """Ship an out-of-round uplink naming the given dead local ranks
        (already removed from ``_local``), relay the root's ABORT answer to
        the survivors, and sever.  Idempotent per rank."""
        fresh = [r for r in ranks if r not in self._reported_dead]
        if not fresh or self._stop.is_set():
            return
        self._reported_dead.update(fresh)
        up = self._up
        if up is None:
            return
        payload = struct.pack("<II", _HUP_MAGIC, len(fresh))
        payload += b"".join(struct.pack("<I", r) for r in fresh)
        payload += struct.pack("<III", 0, 0, 0)   # agg_nranks, n_sub, n_mon
        if _write_frame(up, payload):
            # Counted apart from the per-round uplinks: the one-uplink-
            # per-round frame guard must not see teardown reports.
            self.stats.dead_reports += 1
            # The root answers with the ABORT; fan it to the survivors.
            frame = _read_frame(up, self._stop)
            if frame is not None:
                self._fan_down(frame)
        self._sever_local()

    def _sever_local(self) -> None:
        for s in self._local.values():
            try:
                s.shutdown(socket.SHUT_RDWR)
            except OSError:
                pass

    def _retire_left(self, sel) -> None:
        """Retire ranks whose clean LEAVE completed a round: drop their
        socket and shrink ``ranks`` so the next warm round's aggregate
        section counts only the survivors — the host's uplink SHRINKS
        instead of the whole host dying.  Called after the leave round's
        uplink went out (the root needs the verbatim LEAVE subframe) and
        before the response fan-out (no response is owed to a leaver)."""
        for rank in sorted(self._left_pending):
            s = self._local.pop(rank, None)
            self._bufs.pop(rank, None)
            if s is not None:
                try:
                    sel.unregister(s)
                except (KeyError, ValueError):
                    pass   # EOF handling already unregistered it
                try:
                    s.close()
                except OSError:
                    pass
            if rank in self.ranks:
                self.ranks.remove(rank)
            self.stats.leaves_forwarded += 1
        self._left_pending.clear()

    def _build_uplink(self, frames: Dict[int, bytes]) -> bytes:
        """Fold one round's local frames into the host uplink."""
        subs: List[Tuple[int, bytes]] = []
        mons: List[Tuple[int, bytes]] = []
        cores: List[bytes] = []
        aggregatable = True
        for rank in sorted(frames):
            data = frames[rank]
            parsed = split_rank_frame(data)
            if parsed is None:
                subs.append((rank, data))       # opaque: forward verbatim
                aggregatable = False
                continue
            n_ann, n_tag, core_end, trailing = parsed
            kept = b"".join(struct.pack("<II", m, len(p)) + p
                            for m, p in trailing if m != _MON_MAGIC)
            for m, p in trailing:
                if m == _MON_MAGIC:
                    mons.append((rank, p))
            # A trailing ZRT7 speculation confirm (protocol v7) is part of
            # the warm steady-state shape: when every local rank sends an
            # identical one it rides the core-equality check below and
            # collapses into the aggregate like the bitvector it confirms
            # (the root's confirm accounting is advisory; the announce
            # itself is the aggregate bitvector).  Any OTHER trailing
            # section still forces the per-rank path.
            warm_trailing = all(m == _ZRT_MAGIC and len(p) == 1
                                for m, p in trailing if m != _MON_MAGIC)
            stripped = data[:core_end] + kept
            if n_ann or n_tag or (kept and not warm_trailing):
                subs.append((rank, stripped))
                aggregatable = False
            else:
                cores.append(stripped)
                subs.append((rank, stripped))   # provisional; dropped below
        agg_bv = None
        if aggregatable and cores and len(cores) == len(self.ranks) \
                and all(c == cores[0] for c in cores):
            # The synchronized warm steady state: every local rank sent a
            # pure bitvector frame with identical bits — ONE fixed-size
            # aggregate section replaces them all.
            (bv_len,) = struct.unpack_from("<I", cores[0], 4)
            agg_bv = cores[0][8:8 + bv_len]
            subs = []
        payload = struct.pack("<II", _HUP_MAGIC, 0)
        if agg_bv is not None:
            payload += struct.pack("<II", len(self.ranks), len(agg_bv))
            payload += agg_bv
            self.stats.agg_rounds += 1
        else:
            payload += struct.pack("<I", 0)
        payload += struct.pack("<I", len(subs))
        for rank, data in subs:
            payload += struct.pack("<II", rank, len(data)) + data
        self.stats.subframes_forwarded += len(subs)
        payload += struct.pack("<I", len(mons))
        for rank, blob in mons:
            payload += struct.pack("<II", rank, len(blob)) + blob
        self.stats.mon_blobs_forwarded += len(mons)
        if agg_bv is not None and not mons:
            self.stats.last_agg_uplink_len = len(payload)
        return payload

    def _fan_down(self, frame: bytes) -> List[int]:
        """Relay one downlink frame to every live local rank; returns the
        ranks whose write failed (popped from ``_local`` — the CALLER must
        report them upstream via ``_report_dead``, or the root would keep
        getting complete rounds from the survivors and never learn of the
        death)."""
        dead_writes = []
        for rank, s in list(self._local.items()):
            if not _write_frame(s, frame):
                dead_writes.append(rank)
        self.stats.responses_fanned += 1
        for rank in dead_writes:
            self._local.pop(rank, None)
        return dead_writes

    def _run(self) -> None:
        sel = None
        try:
            if not self._accept_local():
                return
            if not self._connect_upstream():
                # Local clients are already blocked in their first round:
                # sever them so they fail typed instead of hanging.
                self._sever_local()
                return
            # One long-lived selector (epoll on Linux — not select(),
            # whose FD_SETSIZE the negotiation-scaling bench's hundreds of
            # in-process sockets would blow past), registered ONCE per
            # connection like the root's poller — never rebuilt per round.
            sel = selectors.DefaultSelector()
            for r, s in self._local.items():
                sel.register(s, selectors.EVENT_READ, r)
            sel.register(self._up, selectors.EVENT_READ, None)
            while not self._stop.is_set() and self._local:
                frames = self._gather_local(sel)
                if frames is None:
                    return
                self.stats.rounds += 1
                uplink = self._build_uplink(frames)
                if not _write_frame(self._up, uplink):
                    # Root died: sever local ranks so their in-flight
                    # rounds fail typed (unattributed, like flat mode).
                    self._sever_local()
                    return
                self.stats.uplink_frames += 1
                self.stats.uplink_bytes += len(uplink) + 4
                resp = _read_frame(self._up, self._stop)
                if resp is None:
                    self._sever_local()
                    return
                if self._left_pending:
                    self._retire_left(sel)
                dead_writes = self._fan_down(resp)
                if len(resp) >= 4 and struct.unpack_from(
                        "<I", resp)[0] == _ABORT_ESCAPE:
                    # Typed fleet abort: the control plane is done.
                    self._sever_local()
                    return
                if dead_writes or self._deferred_dead:
                    # A rank died between its round send and the response
                    # fan-out: report it NOW — its silence would otherwise
                    # be invisible upstream (the survivors keep completing
                    # rounds, so no deadline ever fires for it).
                    self._report_dead(dead_writes + self._deferred_dead)
                    return
        except Exception as exc:  # noqa: BLE001 - never kill the host process
            self.error = repr(exc)
            log.exception("host agent %d failed", self.host_index)
            self._sever_local()
        finally:
            if sel is not None:
                sel.close()
