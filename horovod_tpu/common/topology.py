"""Device topology and mesh construction.

TPU-native replacement for the reference's transport contexts
(``horovod/common/mpi/mpi_context.cc``, ``horovod/common/gloo/gloo_context.cc``
— SURVEY.md §1 L0): instead of owning MPI communicators, we own
``jax.sharding.Mesh`` objects laid out over the TPU slice's ICI topology.

Rank model (TPU-first, see DESIGN.md):

- a *rank* is a **device** (chip), not a process.  ``size()`` is the global
  device count.  In multi-host SPMD each process contributes its local
  devices; in the hermetic test tier a single process holds 8 virtual CPU
  devices and therefore "is" all ranks at once — the same model as
  ``jax.pmap``-style data parallelism.
- ``local_rank``/``local_size`` describe devices within a process (host);
  ``cross_rank``/``cross_size`` describe the host grid — exactly the
  local/cross communicator split the reference uses for hierarchical
  allreduce (``horovod/common/mpi/mpi_context.cc``).

Device order: ranks are assigned in ICI-topology-aware order (sorted by torus
coordinates when available) so that ring-structured collectives ride
neighboring ICI links — the analogue of the reference launcher's host-slot
ordering.
"""

from __future__ import annotations

import dataclasses
from typing import List, Optional, Sequence, Tuple

import jax
import numpy as np
from jax.sharding import Mesh


def _device_sort_key(d: jax.Device):
    """Sort devices so ring order follows the ICI torus when available.

    Slice index sorts FIRST: in a multi-slice world each slice owns its own
    coordinate system, and the two-level data plane
    (``horovod_tpu/parallel/topology.py``) requires slice membership to be
    contiguous equal rank blocks — interleaving slices by raw coords would
    break the (cross, local) mesh reshape and put DCN hops inside the
    "local" axis."""
    coords = getattr(d, "coords", None)
    slice_idx = getattr(d, "slice_index", 0) or 0
    if coords is not None:
        core = getattr(d, "core_on_chip", 0)
        return (slice_idx, 0, tuple(coords), core, d.id)
    return (slice_idx, 1, (), 0, d.id)


def ordered_devices(devices: Optional[Sequence[jax.Device]] = None) -> List[jax.Device]:
    devs = list(devices) if devices is not None else list(jax.devices())
    devs.sort(key=_device_sort_key)
    return devs


@dataclasses.dataclass
class Topology:
    """Global view of the device world."""

    devices: List[jax.Device]
    mesh: Mesh                       # 1-D mesh over all ranks, axis = world axis
    axis_name: str
    local_counts: List[int]          # devices per process, by process index
    my_process: int
    num_processes: int

    @property
    def size(self) -> int:
        return len(self.devices)

    @property
    def local_size(self) -> int:
        return self.local_counts[self.my_process]

    @property
    def local_rank_of(self) -> dict:
        """rank -> local rank within its process."""
        out = {}
        for r, d in enumerate(self.devices):
            out[r] = sum(1 for r2, d2 in enumerate(self.devices[:r])
                         if d2.process_index == d.process_index)
        return out

    def ranks_of_process(self, process_index: int) -> List[int]:
        return [r for r, d in enumerate(self.devices)
                if d.process_index == process_index]

    def hierarchical_mesh(self, axis_names: Tuple[str, str] = ("cross", "local")) -> Mesh:
        """2-D (host × local-device) mesh for hierarchical collectives.

        Reference parity: the NCCL-intra + MPI-inter two-level allreduce
        (``horovod/common/ops/nccl_operations.cc`` hierarchical path) maps to
        a (cross, local) mesh where the ``local`` axis rides ICI within a
        host and ``cross`` spans hosts (DCN between slices).
        """
        n_local = self.local_counts[0]
        if any(c != n_local for c in self.local_counts):
            raise ValueError(
                f"hierarchical mesh requires uniform local device counts, got {self.local_counts}")
        arr = np.array(self.devices, dtype=object).reshape(self.num_processes, n_local)
        return Mesh(arr, axis_names)


def build_topology(axis_name: str = "hvd",
                   devices: Optional[Sequence[jax.Device]] = None) -> Topology:
    devs = ordered_devices(devices)
    arr = np.array(devs, dtype=object)
    mesh = Mesh(arr, (axis_name,))
    num_processes = max((d.process_index for d in devs), default=0) + 1
    local_counts = [0] * num_processes
    for d in devs:
        local_counts[d.process_index] += 1
    return Topology(
        devices=devs,
        mesh=mesh,
        axis_name=axis_name,
        local_counts=local_counts,
        my_process=jax.process_index(),
        num_processes=num_processes,
    )


def torus_dims(devices: Optional[Sequence[jax.Device]] = None) -> Optional[Tuple[int, ...]]:
    """Physical torus extent of the slice, or None when coords are unknown.

    Used by Adasum (``horovod_tpu/parallel/adasum.py``) to map
    halving-doubling rounds onto physical ICI axes.
    """
    devs = list(devices) if devices is not None else list(jax.devices())
    coords = [getattr(d, "coords", None) for d in devs]
    if any(c is None for c in coords) or not coords:
        return None
    arr = np.array(coords)
    return tuple(int(x) for x in (arr.max(axis=0) - arr.min(axis=0) + 1))
