"""Core runtime state and the ``init``/``rank``/``size`` API family.

TPU-native equivalent of the reference's Python core
(``horovod/common/basics.py`` ``HorovodBasics`` — SURVEY.md §2b P1) fused with
the C++ ``InitializeHorovodOnce`` bootstrap (``horovod/common/operations.cc``
— SURVEY.md §2a N1).  Where the reference ctypes into a C++ global state, we
keep a Python-side ``GlobalState`` that owns the topology, process-set table,
config, timeline and the collective engine; the native TCP controller (multi-
process mode) is attached underneath when launched by ``torovodrun``.

Rank model (see ``topology.py``): a rank is a device.  In multi-process
launches (one process per device, or one per host) ``rank()`` returns this
process's first device's global rank, matching Horovod's process-rank
semantics; in single-process SPMD mode ``rank()`` is 0 and per-rank identity
lives inside ``shard_map`` (``ops.axis_rank``).
"""

from __future__ import annotations

import atexit
import contextlib
import threading
from typing import List, Optional, Sequence

import jax

from .config import Config
from .process_sets import ProcessSet, ProcessSetTable, global_process_set
from .topology import Topology, build_topology


class NotInitializedError(RuntimeError):
    def __init__(self):
        super().__init__("horovod_tpu has not been initialized; call hvd.init() first.")


def _env_has_rendezvous() -> bool:
    import os
    return bool(os.environ.get("HOROVOD_RENDEZVOUS_ADDR"))





class GlobalState:
    def __init__(self):
        self.initialized = False
        self.config: Optional[Config] = None
        self.topology: Optional[Topology] = None
        self.process_set_table = ProcessSetTable()
        self.engine = None          # ops.engine.CollectiveEngine
        self.timeline = None        # utils.timeline.Timeline
        self.controller = None      # multi-process TCP controller client
        self.host_agent = None      # common.host_agent.HostAgent (v5, owned
                                    # by the local_rank-0 process per host)
        self.monitor = None         # monitor.MonitorAgent (HOROVOD_MONITOR)
        self._lock = threading.Lock()


_state = GlobalState()

# Elastic carryover across init/shutdown cycles within ONE worker process
# (ISSUE 12): a re-rendezvous tears the runtime down and re-forms it, but
# some state is keyed on the HOST/process, not the generation — the
# per-host agent object (held on GlobalState across shutdowns so its
# listen port survives) and the zero-RTT engagement hint captured from the
# dying generation's controller (seeds the next generation's server slot
# streaks and client consumption gate, so warm speculation re-engages in
# O(1) rounds instead of relearning from zero).
_elastic_carry = {"spec_seed": 0}


def _get_state() -> GlobalState:
    return _state


def init(process_sets: Optional[Sequence[ProcessSet]] = None,
         devices=None,
         axis_name: str = "hvd") -> None:
    """Initialize the runtime.  Idempotent, like ``hvd.init()``.

    Equivalent call stack in the reference: SURVEY.md §3.1 — env parsing,
    controller selection, background thread spawn.  Here: parse config,
    build the device topology/mesh, register process sets, start the
    collective engine (cycle thread + fusion + cache), connect to the
    launcher's controller when running multi-process.
    """
    st = _state
    with st._lock:
        if st.initialized:
            return
        st.config = Config.from_env()

        # Elastic workers fetch rank/size/coordinator from the driver's
        # versioned rendezvous instead of static env (SURVEY.md §3.4).
        if st.config.elastic and _env_has_rendezvous():
            from ..elastic.worker import elastic_bootstrap
            st.config = elastic_bootstrap()

        # Multi-process bootstrap (launched by torovodrun, SURVEY.md §3.3):
        # jax.distributed forms the global device world at controller_port;
        # the native negotiation controller lives at controller_port + 1.
        cfg = st.config
        multi_process = (cfg.controller_addr != ""
                         and (cfg.size_env > 1 or cfg.elastic))
        # NB: must not touch jax.devices()/process_count() before
        # jax.distributed.initialize — any backend query finalizes the
        # single-process world.
        from jax._src import distributed as _jdist
        if _jdist.global_state.client is None:
            # torovodrun spawns one process per rank (reference §3.3) and
            # provides the coordinator; in pod mode
            # (HOROVOD_ONE_PROC_PER_HOST) each process drives ALL its
            # local devices — the process world still forms at the
            # launcher's coordinator when one is given (rank/size env are
            # PROCESS values there), and falls back to TPU-metadata
            # auto-detection without one (SPMD-only: the eager engine's
            # negotiation controller needs a launcher; enqueue guards it).
            if multi_process and cfg.elastic:
                # Elastic worlds neutralize the XLA coordination service's
                # own failure detector (it can only abort survivors; our
                # control plane detects dead peers in ms and the driver
                # owns recovery) so a post-fault teardown can park the
                # poisoned world instead of dying in its shutdown barrier.
                from ..elastic.worker import init_distributed_resilient
                init_distributed_resilient(
                    f"{cfg.controller_addr}:{cfg.controller_port}",
                    num_processes=cfg.size_env, process_id=cfg.rank_env)
            elif multi_process:
                jax.distributed.initialize(
                    coordinator_address=(
                        f"{cfg.controller_addr}:{cfg.controller_port}"),
                    num_processes=cfg.size_env,
                    process_id=cfg.rank_env,
                )
            elif cfg.one_proc_per_host and not cfg.controller_addr:
                jax.distributed.initialize()

        st.topology = build_topology(axis_name=axis_name, devices=devices)
        gs = st.process_set_table.initialize(
            st.topology.devices, axis_name, extra_sets=process_sets)
        # Rebind the module-level global_process_set singleton.
        global_process_set.__dict__.update(gs.__dict__)
        st.process_set_table._sets[0] = global_process_set

        from ..utils.timeline import Timeline
        st.timeline = Timeline(cfg.timeline_filename,
                               mark_cycles=cfg.timeline_mark_cycles)

        # Wire-visible auto-name counters must restart with the runtime so
        # elastic re-inits leave every rank's name sequence aligned.
        from ..ops import eager as _eager
        _eager.reset_name_counters()

        from ..ops.engine import CollectiveEngine
        st.engine = CollectiveEngine(st)
        if multi_process:
            from .controller import TCPController
            ctrl_port = (cfg.controller_port2 if cfg.controller_port2
                         else cfg.controller_port + 1)
            connect_addr, connect_port = cfg.controller_addr, ctrl_port
            server_port = None
            hier = cfg.hierarchical_controller
            if hier and (cfg.local_rank_env < 0 or cfg.local_size_env <= 0
                         or cfg.cross_rank_env < 0):
                # Manual launches may set only RANK/SIZE/CONTROLLER_ADDR
                # (enough for flat mode).  Deriving a host topology from
                # the -1 defaults would give every process local_rank 0 on
                # cross_rank 0 — each trying to bind its own agent on ONE
                # derived port (EADDRINUSE out of init()).  Fall back to
                # the flat plane loudly instead.
                from ..utils.logging import get_logger
                get_logger().warning(
                    "HOROVOD_HIERARCHICAL_CONTROLLER=1 but HOROVOD_"
                    "LOCAL_RANK/LOCAL_SIZE/CROSS_RANK are not set (launch "
                    "through torovodrun to get them); using the flat "
                    "control plane")
                hier = False
            if hier:
                # Two-level control plane (protocol v5): ranks talk to a
                # per-host agent that presents the whole host to the root
                # as ONE connection (common/host_agent.py).  The
                # local_rank-0 process owns its host's agent; rank 0 still
                # hosts the root server at the launcher-advertised port
                # while its own client goes through host 0's agent like
                # everyone else's.  Elastic worlds compose (ISSUE 12): the
                # agent object SURVIVES re-rendezvous generations — keyed
                # on the host, listening on the stable per-host port the
                # elastic driver allocated (HOROVOD_AGENT_PORT via the
                # rendezvous assignment) — and each generation re-forms
                # its uplink/local connections via new_generation.
                from .host_agent import HostAgent
                local_rank = cfg.local_rank_env
                local_size = cfg.local_size_env
                cross_rank = cfg.cross_rank_env
                agent_port = (cfg.agent_port
                              or ctrl_port + 1 + cross_rank)
                if local_rank == 0:
                    first = cfg.rank_env - local_rank
                    ranks = list(range(first,
                                       min(cfg.size_env,
                                           first + local_size)))
                    reused = False
                    if (st.host_agent is not None and cfg.elastic
                            and st.host_agent.port == agent_port):
                        try:
                            st.host_agent.new_generation(
                                cfg.controller_addr, ctrl_port, ranks,
                                host_index=cross_rank)
                            reused = True
                        except RuntimeError:
                            # A wedged previous-generation thread: fall
                            # back to a fresh agent on the same port
                            # (stop() closes the listener first).
                            from ..utils.logging import get_logger
                            get_logger().warning(
                                "host agent could not serve a new "
                                "generation; replacing it")
                    if not reused:
                        if st.host_agent is not None:
                            st.host_agent.stop()
                        st.host_agent = HostAgent(
                            agent_port, cfg.controller_addr, ctrl_port,
                            ranks, host_index=cross_rank).start()
                connect_addr, connect_port = "127.0.0.1", agent_port
                if cfg.rank_env == 0:
                    server_port = ctrl_port
            # Zero-RTT streak carryover (ISSUE 12): a surviving elastic
            # worker seeds the new generation from the hint captured at
            # the previous shutdown — 0 on the first generation and in
            # non-elastic worlds.
            spec_carry = _elastic_carry["spec_seed"] if cfg.elastic else 0
            st.controller = TCPController(
                connect_addr, connect_port,
                rank=cfg.rank_env, world=cfg.size_env,
                stall_warn_s=cfg.stall_check_time_s
                if not cfg.stall_check_disable else 1e18,
                cache_capacity=cfg.response_cache_capacity,
                round_timeout_s=cfg.round_timeout_s,
                connect_retries=cfg.connect_retries,
                connect_backoff_ms=cfg.connect_backoff_ms,
                server_port=server_port,
                spec_ready_after=cfg.spec_ready_after,
                round_pipeline=cfg.round_pipeline,
                spec_seed=spec_carry,
                spec_streak_hint=spec_carry)
            st.engine.controller = st.controller

        if cfg.monitor:
            # Cross-rank telemetry & health subsystem (docs/monitoring.md):
            # per-rank registry + coordinator side-channel aggregation; the
            # HTTP exporter serves /metrics + /health on rank 0 when a
            # port is configured.  Installed before engine.start() so the
            # very first cycle is observed.
            from ..monitor.agent import MonitorAgent
            mon_rank = cfg.rank_env if cfg.rank_env >= 0 else 0
            mon_world = cfg.size_env if (multi_process
                                         and cfg.size_env > 0) else 1
            st.monitor = MonitorAgent(
                engine=st.engine, controller=st.controller,
                rank=mon_rank, world=mon_world,
                interval_s=cfg.monitor_interval_s, timeline=st.timeline)
            if cfg.monitor_port > 0 and mon_rank == 0:
                try:
                    st.monitor.serve_http(cfg.monitor_port)
                except OSError as exc:
                    # A taken port must not kill training — the telemetry
                    # plane is strictly best-effort.
                    from ..utils.logging import get_logger
                    get_logger().warning(
                        "monitor: could not bind HTTP port %d (%s); "
                        "exporter disabled", cfg.monitor_port, exc)
        st.engine.start()

        st.initialized = True


def shutdown() -> None:
    st = _state
    with st._lock:
        if not st.initialized:
            return
        # A control-plane fault (dead peer — HVD303) means the jax world's
        # cooperative teardown can never complete: take the abrupt path
        # below.  Captured before the engine is torn down.
        abrupt = (st.engine is not None
                  and getattr(st.engine, "fault", None) is not None)
        # Peers that departed via clean LEAVE (protocol v6): not a fault,
        # but the cooperative jax teardown barrier can no longer complete
        # either — the survivors must park, exactly like the fault path,
        # just without the HVD303 noise.
        peers_left = bool(getattr(st.controller, "left_ranks", None)) \
            if st.controller is not None else False
        leave_sent = False
        if st.controller is not None and st.engine is not None \
                and not abrupt:
            # Clean departure (protocol v6): quiesce the cycle thread at a
            # round boundary — the in-flight lock-step round completes in
            # a healthy world — then announce the LEAVE on the quiet
            # socket BEFORE the sever, so the coordinator drops this rank
            # from the gather instead of survivors eating a dead-peer
            # verdict.  A wedged thread (a peer already died) falls back
            # to the legacy interrupt-and-sever below; a pre-v6 server
            # makes leave() a no-op.
            if st.engine.quiesce(timeout=5.0) and \
                    getattr(st.engine, "fault", None) is None:
                leave_sent = st.controller.leave()
            else:
                abrupt = abrupt or (
                    getattr(st.engine, "fault", None) is not None)
        if st.controller is not None:
            # Unblock any lock-step round FIRST so the engine thread can't
            # be left inside the native client when we free it.
            st.controller.interrupt()
        if st.engine is not None:
            st.engine.stop()
            st.engine = None
        if st.monitor is not None:
            st.monitor.close()
            st.monitor = None
        elastic_world = (st.config is not None and st.config.elastic
                         and st.config.controller_addr != "")
        if st.controller is not None:
            # Zero-RTT streak carryover (ISSUE 12): capture the dying
            # generation's engagement hint before the controller goes
            # away, so a survivor's re-init re-engages speculation in
            # O(1) rounds.  A faulted generation carries nothing — and
            # must also CLEAR any older hint, or a stale seed from two
            # generations back would leak past the instability that just
            # killed this one.
            if elastic_world:
                if abrupt:
                    _elastic_carry["spec_seed"] = 0
                else:
                    try:
                        _elastic_carry["spec_seed"] = \
                            st.controller.spec_carry_hint()
                    except Exception:  # noqa: BLE001 - telemetry only
                        _elastic_carry["spec_seed"] = 0
            st.controller.shutdown()
            st.controller = None
        if st.host_agent is not None:
            # After the controller: the agent must outlive this process's
            # own client socket so its teardown EOF is observed (and
            # reported upstream) rather than racing a dead agent thread.
            # Elastic worlds only END the generation (ISSUE 12): the agent
            # object — and its stable listen port — survives for the next
            # re-rendezvous generation's new_generation.
            if elastic_world:
                st.host_agent.end_generation()
            else:
                st.host_agent.stop()
                st.host_agent = None
        if st.timeline is not None:
            st.timeline.close()
            st.timeline = None
        # Elastic resets must fully tear down the JAX world so the next
        # init() can re-form it with a different size (mesh invalidation —
        # SURVEY.md §7 hard-part #3).
        if (st.config is not None and st.config.elastic
                and st.config.controller_addr != ""):
            from ..elastic.worker import (exit_guard_note_clean_shutdown,
                                          teardown_distributed)
            # A clean LEAVE — ours (leave_sent: the peers are NOT shutting
            # down, so the cooperative barrier would hang waiting for
            # them) or a peer's (peers_left: the departed rank will never
            # join it) — parks the world like the fault path; only a
            # full-world synchronized shutdown can take the graceful
            # barrier.
            teardown_distributed(abrupt=abrupt or leave_sent or peers_left)
            if not abrupt:
                # A non-abrupt explicit shutdown means the run completed:
                # any exit code latched by a caught-and-recovered
                # sys.exit() is stale.  Clean leaves count — the departure
                # was orderly.
                exit_guard_note_clean_shutdown()
        st.initialized = False
        st.topology = None


atexit.register(shutdown)


def is_initialized() -> bool:
    return _state.initialized


def _topo() -> Topology:
    if not _state.initialized or _state.topology is None:
        raise NotInitializedError()
    return _state.topology


def _cfg() -> Config:
    cfg = _state.config
    assert cfg is not None
    return cfg


def size() -> int:
    """Global number of ranks (devices), like ``hvd.size()``."""
    return _topo().size


def rank() -> int:
    """This process's rank.

    Launcher-provided HOROVOD_RANK wins (one-process-per-device launches);
    otherwise the global rank of this process's first local device.  In
    pod mode (HOROVOD_ONE_PROC_PER_HOST) the env value describes the
    PROCESS world for the control plane, not the device world — rank() is
    always topology-derived there so ``dataset.shard(size(), rank())``
    stays consistent with size() on multi-chip hosts.
    """
    t = _topo()
    cfg = _cfg()
    if cfg.rank_env >= 0 and not cfg.one_proc_per_host:
        return cfg.rank_env
    mine = t.ranks_of_process(t.my_process)
    return mine[0] if mine else 0


def local_size() -> int:
    cfg = _cfg()
    if cfg.local_size_env > 0 and not cfg.one_proc_per_host:
        return cfg.local_size_env
    return _topo().local_size


def local_rank() -> int:
    """Rank of this process's first device within its host.

    Launcher-provided HOROVOD_LOCAL_RANK wins (it knows host boundaries
    even when several single-device processes share one physical host);
    otherwise — and always in pod mode — derived from the device topology.
    """
    cfg = _cfg()
    if cfg.local_rank_env >= 0 and not cfg.one_proc_per_host:
        return cfg.local_rank_env
    t = _topo()
    mine = t.ranks_of_process(t.my_process)
    if not mine:
        return 0
    return t.local_rank_of[mine[0]]


def cross_size() -> int:
    """Number of hosts, like ``hvd.cross_size()``."""
    env = _cfg().cross_size_env
    return env if env > 0 else _topo().num_processes


def cross_rank() -> int:
    env = _cfg().cross_rank_env
    return env if env >= 0 else _topo().my_process


def mesh():
    """The global 1-D world mesh (axis name = ``hvd``)."""
    return _topo().mesh


def is_homogeneous() -> bool:
    t = _topo()
    return all(c == t.local_counts[0] for c in t.local_counts)


def add_process_set(ps_or_ranks) -> ProcessSet:
    st = _state
    if not st.initialized:
        raise NotInitializedError()
    ps = ps_or_ranks if isinstance(ps_or_ranks, ProcessSet) else ProcessSet(ps_or_ranks)
    assert st.topology is not None and st.config is not None
    return st.process_set_table.add(ps, st.topology.devices, st.config.mesh_axis_name)


def remove_process_set(ps: ProcessSet):
    if not _state.initialized:
        raise NotInitializedError()
    _state.process_set_table.remove(ps)


def process_set_included(ps: ProcessSet) -> bool:
    return ps.included(rank())


# Capability probes, for API parity with HorovodBasics (reference
# horovod/common/basics.py: nccl_built/mpi_enabled/...).  On TPU the data
# plane is always XLA collectives, so these report the analogous truths.
def xla_built() -> bool:
    return True


def nccl_built() -> bool:
    return False


def mpi_enabled() -> bool:
    return False


def gloo_enabled() -> bool:
    return False


def mpi_threads_supported() -> bool:
    return False


def cuda_built() -> bool:
    return False


def rocm_built() -> bool:
    return False


def tpu_available() -> bool:
    return any(d.platform == "tpu" for d in jax.devices())


def start_timeline(filename: str, mark_cycles: bool = False):
    """Begin writing a Chrome-trace timeline (reference: timeline.cc N10)."""
    st = _state
    if not st.initialized:
        raise NotInitializedError()
    from ..utils.timeline import Timeline
    if st.timeline is not None:
        st.timeline.close()
    st.timeline = Timeline(filename, mark_cycles=mark_cycles)


def stop_timeline():
    st = _state
    if not st.initialized:
        raise NotInitializedError()
    if st.timeline is not None:
        st.timeline.close()
    from ..utils.timeline import Timeline
    st.timeline = Timeline("", mark_cycles=False)


def start_profile(logdir: str):
    """Start a device-level profiler trace (XProf/TensorBoard format).

    The coordinator's own Chrome-trace timeline (``start_timeline``, the
    reference's N10) covers NEGOTIATE/XLA phases per tensor; this is the
    complementary device view SURVEY.md §5 calls for — XLA op timing, HBM
    traffic, ICI collectives — via ``jax.profiler``.  View with
    ``tensorboard --logdir`` or Perfetto.  One trace at a time.
    """
    jax.profiler.start_trace(logdir)


def stop_profile():
    """Stop the trace started by :func:`start_profile` and flush it."""
    jax.profiler.stop_trace()


@contextlib.contextmanager
def profile_step(logdir: str):
    """Context manager profiling one region (e.g. a train step)::

        with hvd.profile_step("/tmp/prof"):
            params, opt_state, loss = train_step(...)
    """
    start_profile(logdir)
    try:
        yield
    finally:
        stop_profile()
