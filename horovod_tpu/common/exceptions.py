"""Typed control-plane error taxonomy (no jax imports).

The reference signals world-level failures through ``HorovodInternalError``
(``horovod/common/exceptions.py``): the elastic ``run`` wrapper catches it,
restores the last committed state, and re-rendezvouses (SURVEY.md §3.4).
This module is the jax-free home of that hierarchy so the TCP controller,
the fault-injection harness (``horovod_tpu/testing``) and the monitor
subsystem can all raise/inspect typed failures without dragging jax into
the fast test tier.  ``elastic/state.py`` re-exports
``HorovodInternalError`` for backwards compatibility.

Taxonomy::

    RuntimeError
     └─ HorovodInternalError          world-level failure; elastic resets
         └─ ControlPlaneError         coordinator control plane failed
             ├─ PeerFailureError      HVD303: a peer died / was declared
             │                        dead (carries the dead-rank list)
             └─ RoundTimeoutError     HVD303: this rank's negotiation
                                      round exceeded its wall-clock
                                      deadline (peers unattributable)
    TimeoutError
     └─ JoinTimeoutError              hvd.join() did not complete in time

``NegotiationError`` (an application-level per-tensor failure, deliberately
NOT a HorovodInternalError) stays in ``common/controller.py``.
"""

from __future__ import annotations

from typing import Optional, Sequence


class HorovodInternalError(RuntimeError):
    """A peer died mid-collective; training must roll back to last commit.

    The elastic ``@hvd.elastic.run`` wrapper catches this, restores the
    last committed state, re-initializes the runtime and re-rendezvouses
    with the surviving host set; without the wrapper it propagates as a
    plain RuntimeError (static jobs fail fast instead of hanging).
    """


class ControlPlaneError(HorovodInternalError):
    """The coordinator control plane failed (dead peer, abort broadcast,
    or a missed deadline).  Base class for the HVD303 family — catch this
    to handle any control-plane fault uniformly."""


class PeerFailureError(ControlPlaneError):
    """HVD303: the coordinator declared one or more peer ranks dead.

    Raised on surviving ranks when the server broadcasts a typed ABORT
    (a peer's socket died or it missed the per-round deadline), or when
    this rank's own connection to the coordinator was severed.  Carries
    the dead-rank attribution when known.

    Attributes:
        dead_ranks: sorted list of ranks the server declared dead
            (empty when the failure could not be attributed — e.g. the
            coordinator itself vanished before naming anyone).
        reason: the server's verdict string (connection loss vs missed
            deadline, and in which round).
    """

    def __init__(self, message: str,
                 dead_ranks: Optional[Sequence[int]] = None,
                 reason: str = ""):
        super().__init__(message)
        self.dead_ranks = sorted(dead_ranks or [])
        self.reason = reason


class RoundTimeoutError(ControlPlaneError):
    """HVD303: a negotiation round exceeded ``HOROVOD_ROUND_TIMEOUT_S``.

    Raised by the client when the coordinator's response did not arrive
    inside the wall-clock deadline — the coordinator (or the laggard rank
    gating the lock-step round) is wedged but its socket is still open, so
    no dead-rank attribution is available from the wire; the monitor
    aggregator may still enrich the message with per-rank snapshot ages.

    Attributes:
        timeout_s: the deadline that expired.
    """

    def __init__(self, message: str, timeout_s: float = 0.0):
        super().__init__(message)
        self.timeout_s = timeout_s


class JoinTimeoutError(TimeoutError):
    """``hvd.join()`` did not complete within the caller's timeout.

    Contract: ``join_wait(timeout=)`` either returns the last rank to
    join (an ``int >= 0``) or raises this — it never returns a sentinel.
    Subclasses ``TimeoutError`` so pre-existing ``except TimeoutError``
    call sites keep working."""
