"""Typed control-plane error taxonomy (no jax imports).

The reference signals world-level failures through ``HorovodInternalError``
(``horovod/common/exceptions.py``): the elastic ``run`` wrapper catches it,
restores the last committed state, and re-rendezvouses (SURVEY.md §3.4).
This module is the jax-free home of that hierarchy so the TCP controller,
the fault-injection harness (``horovod_tpu/testing``) and the monitor
subsystem can all raise/inspect typed failures without dragging jax into
the fast test tier.  ``elastic/state.py`` re-exports
``HorovodInternalError`` for backwards compatibility.

Taxonomy::

    RuntimeError
     └─ HorovodInternalError          world-level failure; elastic resets
         └─ ControlPlaneError         coordinator control plane failed
             ├─ PeerFailureError      HVD303: a peer died / was declared
             │                        dead (carries the dead-rank list)
             └─ RoundTimeoutError     HVD303: this rank's negotiation
                                      round exceeded its wall-clock
                                      deadline (peers unattributable)
    TimeoutError
     └─ JoinTimeoutError              hvd.join() did not complete in time
    Exception
     ├─ HostsUpdatedInterrupt        host set changed; re-rendezvous
     │   └─ PeerLeftInterrupt        a peer sent a clean LEAVE (v6) —
     │                               world shrank, NOT a fault
     └─ DrainRequested               the driver asked this worker to
                                     drain: finish batch, LEAVE, exit 0

``NegotiationError`` (an application-level per-tensor failure, deliberately
NOT a HorovodInternalError) stays in ``common/controller.py``.
"""

from __future__ import annotations

from typing import Optional, Sequence


class HorovodInternalError(RuntimeError):
    """A peer died mid-collective; training must roll back to last commit.

    The elastic ``@hvd.elastic.run`` wrapper catches this, restores the
    last committed state, re-initializes the runtime and re-rendezvouses
    with the surviving host set; without the wrapper it propagates as a
    plain RuntimeError (static jobs fail fast instead of hanging).
    """


class ControlPlaneError(HorovodInternalError):
    """The coordinator control plane failed (dead peer, abort broadcast,
    or a missed deadline).  Base class for the HVD303 family — catch this
    to handle any control-plane fault uniformly."""


class PeerFailureError(ControlPlaneError):
    """HVD303: the coordinator declared one or more peer ranks dead.

    Raised on surviving ranks when the server broadcasts a typed ABORT
    (a peer's socket died or it missed the per-round deadline), or when
    this rank's own connection to the coordinator was severed.  Carries
    the dead-rank attribution when known.

    Attributes:
        dead_ranks: sorted list of ranks the server declared dead
            (empty when the failure could not be attributed — e.g. the
            coordinator itself vanished before naming anyone).
        reason: the server's verdict string (connection loss vs missed
            deadline, and in which round).
    """

    def __init__(self, message: str,
                 dead_ranks: Optional[Sequence[int]] = None,
                 reason: str = ""):
        super().__init__(message)
        self.dead_ranks = sorted(dead_ranks or [])
        self.reason = reason


class RoundTimeoutError(ControlPlaneError):
    """HVD303: a negotiation round exceeded ``HOROVOD_ROUND_TIMEOUT_S``.

    Raised by the client when the coordinator's response did not arrive
    inside the wall-clock deadline — the coordinator (or the laggard rank
    gating the lock-step round) is wedged but its socket is still open, so
    no dead-rank attribution is available from the wire; the monitor
    aggregator may still enrich the message with per-rank snapshot ages.

    Attributes:
        timeout_s: the deadline that expired.
    """

    def __init__(self, message: str, timeout_s: float = 0.0):
        super().__init__(message)
        self.timeout_s = timeout_s


class JoinTimeoutError(TimeoutError):
    """``hvd.join()`` did not complete within the caller's timeout.

    Contract: ``join_wait(timeout=)`` either returns the last rank to
    join (an ``int >= 0``) or raises this — it never returns a sentinel.
    Subclasses ``TimeoutError`` so pre-existing ``except TimeoutError``
    call sites keep working."""


class HostsUpdatedInterrupt(Exception):
    """The elastic driver notified a host-set change; re-rendezvous keeping
    current (committed-or-not) parameters.

    Historically defined in ``elastic/state.py``; moved here (jax-free) so
    the controller, the engine and the autoscaling stack can raise it
    without dragging jax into the fast test tier.  ``elastic/state.py``
    re-exports it, so ``isinstance`` checks against either import path see
    ONE class.  Deliberately NOT a :class:`HorovodInternalError`: the
    elastic run wrapper keeps current parameters (no restore) on this
    path."""

    def __init__(self, skip_sync: bool = False):
        self.skip_sync = skip_sync


class PeerLeftInterrupt(HostsUpdatedInterrupt):
    """A peer rank departed with a clean LEAVE (protocol v6) — the world
    must re-form before any more default-process-set collectives run.

    Raised on survivors when the coordinator's leave notice arrives: new
    world-level submissions fail with it immediately, and world-level
    verdicts computed over the shrunk control-plane world are failed with
    it instead of executed (the data-plane world is still the old, fixed
    size — executing would wedge the transport).  A
    :class:`HostsUpdatedInterrupt` subclass: the elastic run wrapper
    re-rendezvouses keeping current parameters, exactly like a
    driver-pinged host change — NOT an HVD303 fault, the departure was
    orderly.

    Attributes:
        left_ranks: sorted ranks that announced a clean LEAVE.
    """

    def __init__(self, left_ranks: Optional[Sequence[int]] = None):
        super().__init__(skip_sync=False)
        self.left_ranks = sorted(left_ranks or [])

    def __str__(self):
        return (f"peer rank(s) {self.left_ranks} left the world cleanly "
                f"(protocol v6 LEAVE); re-rendezvous before submitting "
                f"more world-level collectives")


class DrainRequested(Exception):
    """The elastic driver asked this worker to drain: finish the current
    batch, send a clean LEAVE, and exit 0.

    Delivered through the worker notification channel (the autoscaler's
    scale-in / straggler-evict path) and raised from ``state.commit()`` —
    the same check point as :class:`HostsUpdatedInterrupt`, so the worker
    always drains at a batch boundary with its state committed.  The
    ``@hvd.elastic.run`` wrapper catches it, shuts the runtime down (which
    sends the LEAVE) and returns; the host is NOT blacklisted."""
