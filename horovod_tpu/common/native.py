"""Build + load the native coordinator library via ctypes.

Reference parity: where ``horovod/common/basics.py`` ctypes-loads the
prebuilt ``mpi_lib_v2`` extension (SURVEY.md §2b P1), we compile
``csrc/coordinator.cc`` once (g++ is in the image; no pip/pybind needed) and
cache the .so under the package.  Pure-build-on-first-use keeps the repo
installable without a build step.
"""

from __future__ import annotations

import ctypes
import hashlib
import os
import subprocess
import threading
from typing import Optional

_lock = threading.Lock()
_lib: Optional[ctypes.CDLL] = None

_PKG_DIR = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
_SRC = os.path.join(os.path.dirname(_PKG_DIR), "csrc", "coordinator.cc")
_OUT_DIR = os.path.join(_PKG_DIR, "lib")


def _out_path() -> str:
    """Artifact path keyed on a SOURCE CONTENT hash, not mtime.

    An mtime-keyed rebuild swaps semantics mid-suite: editing
    ``coordinator.cc`` during an in-flight pytest run made the next
    ``load()`` in a *different* process rebuild over the path the first
    process had dlopen'd by name, so one run mixed two protocol versions.
    Hashing the source into the artifact NAME makes every source version a
    distinct file — an already-running process keeps its version, a new
    process builds (or reuses) exactly the version its source says.
    """
    with open(_SRC, "rb") as fh:
        digest = hashlib.sha256(fh.read()).hexdigest()[:16]
    return os.path.join(_OUT_DIR, f"libhvdtpu_coord.{digest}.so")


def _build() -> str:
    os.makedirs(_OUT_DIR, exist_ok=True)
    out = _out_path()
    if os.path.exists(out):
        return out
    # Several worker processes can race to build (e.g. a local -np N launch
    # on fresh source): serialize builds with an flock and write to a
    # pid-unique tmp so a racing process can never observe (or produce) a
    # half-written library.
    import fcntl
    with open(os.path.join(_OUT_DIR, "build.lock"), "w") as lockf:
        fcntl.flock(lockf, fcntl.LOCK_EX)
        if not os.path.exists(out):
            tmp = f"{out}.{os.getpid()}.tmp"
            cmd = ["g++", "-O2", "-std=c++17", "-shared", "-fPIC", "-pthread",
                   _SRC, "-o", tmp]
            try:
                subprocess.run(cmd, check=True, capture_output=True, text=True)
                os.replace(tmp, out)
            finally:
                if os.path.exists(tmp):
                    os.unlink(tmp)
            # Best-effort GC of superseded versions (and the legacy
            # unhashed artifact): a process still running an old version
            # keeps its dlopen handle — unlinking is safe on Linux.
            base = os.path.basename(out)
            for f in os.listdir(_OUT_DIR):
                if (f.startswith("libhvdtpu_coord.") and f.endswith(".so")
                        and f != base):
                    try:
                        os.unlink(os.path.join(_OUT_DIR, f))
                    except OSError:
                        pass
    return out


def load() -> ctypes.CDLL:
    global _lib
    with _lock:
        if _lib is not None:
            return _lib
        path = _build()
        lib = ctypes.CDLL(path)
        lib.hvdtpu_server_start.restype = ctypes.c_void_p
        lib.hvdtpu_server_start.argtypes = [ctypes.c_int, ctypes.c_int,
                                            ctypes.c_double, ctypes.c_int,
                                            ctypes.c_int, ctypes.c_int,
                                            ctypes.c_int]
        lib.hvdtpu_server_stop.argtypes = [ctypes.c_void_p]
        lib.hvdtpu_server_stats.restype = ctypes.c_int
        lib.hvdtpu_server_stats.argtypes = [
            ctypes.c_void_p, ctypes.POINTER(ctypes.c_double)]
        lib.hvdtpu_client_connect.restype = ctypes.c_void_p
        lib.hvdtpu_client_connect.argtypes = [ctypes.c_char_p, ctypes.c_int,
                                              ctypes.c_int, ctypes.c_int]
        lib.hvdtpu_client_round.restype = ctypes.c_int
        lib.hvdtpu_client_round.argtypes = [
            ctypes.c_void_p, ctypes.POINTER(ctypes.c_uint8), ctypes.c_int,
            ctypes.POINTER(ctypes.c_uint8), ctypes.c_int]
        lib.hvdtpu_client_send.restype = ctypes.c_int
        lib.hvdtpu_client_send.argtypes = [
            ctypes.c_void_p, ctypes.POINTER(ctypes.c_uint8), ctypes.c_int]
        lib.hvdtpu_client_recv.restype = ctypes.c_int
        lib.hvdtpu_client_recv.argtypes = [
            ctypes.c_void_p, ctypes.POINTER(ctypes.c_uint8), ctypes.c_int,
            ctypes.c_int]
        lib.hvdtpu_client_pending.restype = ctypes.c_int
        lib.hvdtpu_client_pending.argtypes = [ctypes.c_void_p]
        lib.hvdtpu_client_interrupt.argtypes = [ctypes.c_void_p]
        lib.hvdtpu_client_close.argtypes = [ctypes.c_void_p]
        _lib = lib
        return lib
