"""Build + load the native coordinator library via ctypes.

Reference parity: where ``horovod/common/basics.py`` ctypes-loads the
prebuilt ``mpi_lib_v2`` extension (SURVEY.md §2b P1), we compile
``csrc/coordinator.cc`` once (g++ is in the image; no pip/pybind needed) and
cache the .so under the package.  Pure-build-on-first-use keeps the repo
installable without a build step.
"""

from __future__ import annotations

import ctypes
import os
import subprocess
import threading
from typing import Optional

_lock = threading.Lock()
_lib: Optional[ctypes.CDLL] = None

_PKG_DIR = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
_SRC = os.path.join(os.path.dirname(_PKG_DIR), "csrc", "coordinator.cc")
_OUT_DIR = os.path.join(_PKG_DIR, "lib")
_OUT = os.path.join(_OUT_DIR, "libhvdtpu_coord.so")


def _build() -> str:
    os.makedirs(_OUT_DIR, exist_ok=True)

    def fresh():
        return (os.path.exists(_OUT)
                and os.path.getmtime(_OUT) >= os.path.getmtime(_SRC))

    if fresh():
        return _OUT
    # Several worker processes can hit a stale .so simultaneously (e.g. a
    # local -np N launch after touching the source): serialize builds with an
    # flock and write to a pid-unique tmp so a racing process can never
    # observe (or produce) a half-written library.
    import fcntl
    with open(_OUT + ".lock", "w") as lockf:
        fcntl.flock(lockf, fcntl.LOCK_EX)
        if not fresh():
            tmp = f"{_OUT}.{os.getpid()}.tmp"
            cmd = ["g++", "-O2", "-std=c++17", "-shared", "-fPIC", "-pthread",
                   _SRC, "-o", tmp]
            try:
                subprocess.run(cmd, check=True, capture_output=True, text=True)
                os.replace(tmp, _OUT)
            finally:
                if os.path.exists(tmp):
                    os.unlink(tmp)
    return _OUT


def load() -> ctypes.CDLL:
    global _lib
    with _lock:
        if _lib is not None:
            return _lib
        path = _build()
        lib = ctypes.CDLL(path)
        lib.hvdtpu_server_start.restype = ctypes.c_void_p
        lib.hvdtpu_server_start.argtypes = [ctypes.c_int, ctypes.c_int,
                                            ctypes.c_double, ctypes.c_int]
        lib.hvdtpu_server_stop.argtypes = [ctypes.c_void_p]
        lib.hvdtpu_client_connect.restype = ctypes.c_void_p
        lib.hvdtpu_client_connect.argtypes = [ctypes.c_char_p, ctypes.c_int,
                                              ctypes.c_int, ctypes.c_int]
        lib.hvdtpu_client_round.restype = ctypes.c_int
        lib.hvdtpu_client_round.argtypes = [
            ctypes.c_void_p, ctypes.POINTER(ctypes.c_uint8), ctypes.c_int,
            ctypes.POINTER(ctypes.c_uint8), ctypes.c_int]
        lib.hvdtpu_client_interrupt.argtypes = [ctypes.c_void_p]
        lib.hvdtpu_client_close.argtypes = [ctypes.c_void_p]
        _lib = lib
        return lib
