"""Deterministic test machinery for the control plane (no jax imports).

This package must stay importable with jax hard-blocked — the tier-1
purity guard in ``tests/test_monitor.py`` enforces it — because the fault
points fire inside the controller's negotiation hot path and the
acceptance workers arm them in processes that may not have a device
backend at all.
"""

from .faults import FaultSpec, arm, armed, disarm, fire, spec  # noqa: F401
