"""Deterministic fault-injection harness for the coordinator control plane.

The reference proves its failure paths with integration tests that really
kill workers (``test/integration/test_elastic_torch.py`` SIGKILLs a rank
mid-epoch); reproducing that deterministically needs the kill to land at a
*named protocol point*, not "roughly when the signal arrives".  This
module provides those points: the controller (and anything else on the
control plane) calls :func:`fire` at well-known places, and a single
environment variable arms exactly one of them::

    HVD_TPU_FAULT=<point>:<rank>:<action>[:<nth>]

    point   connect        before the TCP connect to the coordinator
            pre_announce   entering negotiate(), before building announces
            round_send     before the request frame is written
            mid_round_exit after the request is sent, before the response
                           is read (a crash here is the classic
                           "died mid-negotiation" shape: the server has
                           this rank's frame, the rank is gone)
            round_recv     before blocking for the response frame
    rank    the rank the fault targets (other ranks never fire)
    action  crash          os._exit(13) — an unclean process death
            hang           sleep forever (bounded by _HANG_S; trips round
                           deadlines / stall machinery)
            delay_ms=N     sleep N milliseconds, then continue
            econnreset     abruptly sever the controller connection (the
                           caller passes the sever callback), then
                           continue — the peer observes a dead socket
    nth     fire on the nth arrival at that point (default 1); earlier
            arrivals pass through untouched, later ones too (one-shot)

Zero-cost when unarmed: :func:`armed` is a module-flag check, and the
controller caches ``fire`` only when it returns True — an unarmed run
never executes a single instruction of this module on the hot path (the
steady-state frame guard in ``tests/test_response_cache.py`` additionally
proves the wire carries zero extra bytes either way).

No jax imports (tier-1 purity guard).  Thread-safe: the nth-counters are
lock-guarded because fault points fire from the engine cycle thread while
tests may arm/disarm from the main thread.
"""

from __future__ import annotations

import dataclasses
import os
import threading
import time
from typing import Callable, Dict, Optional

from ..utils.logging import get_logger

log = get_logger()

ENV_VAR = "HVD_TPU_FAULT"

POINTS = ("connect", "pre_announce", "round_send", "mid_round_exit",
          "round_recv",
          # Resilient state plane (ISSUE 14, elastic/stateplane.py):
          #   ckpt_write_fail    each shard-chunk write attempt (io_error
          #                      with nth=1 proves retry_with_backoff
          #                      recovers; nth=0 — persistent — proves a
          #                      failed epoch degrades to the previous
          #                      durable one)
          #   ckpt_torn          between the shard rename and the
          #                      manifest rename — a crash/io_error here
          #                      leaves a torn epoch restore must skip
          #   restore_peer_exit  a survivor about to serve a shard —
          #                      econnreset/crash model peer death
          #                      mid-restore (the joiner re-fetches from
          #                      another survivor or falls back to disk)
          "ckpt_write_fail", "ckpt_torn", "restore_peer_exit",
          # Serving plane (ISSUE 20, serve/replica.py): fired once per
          # dispatched BATCH, mid-batch — after the batcher handed the
          # requests over, before results route back.  Usually armed
          # through the serving sugar verbs below rather than spelled
          # out.
          "serve_forward")
ACTIONS = ("crash", "hang", "delay_ms", "econnreset", "io_error")

# Serving chaos sugar (ISSUE 20): operator-facing spellings that expand
# to serve_forward faults.
#
#     replica_crash:<rank>@<nth>     unclean death mid-batch on the nth
#                                    dispatched batch (also accepts ':'
#                                    as the separator)
#     forward_fault:<rank>:<nth>     the nth forward raises an injected
#                                    I/O error (retryable at the front
#                                    door; consecutive repeats feed the
#                                    quarantine)
#     slow_replica:<rank>:<delay_ms> EVERY forward stalls delay_ms
#                                    (persistent; the hedging target)
SERVE_VERBS = ("replica_crash", "forward_fault", "slow_replica")

# Bounded "forever": long enough to trip any reasonable deadline, short
# enough that a leaked daemon thread cannot outlive a CI job by much.
_HANG_S = 3600.0

_EXIT_CODE = 13  # distinct from rc=1 so tests can tell crash from bug


@dataclasses.dataclass(frozen=True)
class FaultSpec:
    """One parsed ``HVD_TPU_FAULT`` directive."""
    point: str
    rank: int
    action: str
    arg: float = 0.0     # delay_ms payload
    nth: int = 1

    @classmethod
    def parse(cls, text: str) -> "FaultSpec":
        text = text.strip()
        head = text.split(":", 1)[0].split("@", 1)[0]
        if head in SERVE_VERBS:
            return cls._parse_serving(text)
        parts = text.split(":")
        if len(parts) not in (3, 4):
            raise ValueError(
                f"{ENV_VAR} must be <point>:<rank>:<action>[:<nth>], "
                f"got {text!r}")
        point, rank_s, action_s = parts[0], parts[1], parts[2]
        nth = int(parts[3]) if len(parts) == 4 else 1
        if point not in POINTS:
            raise ValueError(
                f"{ENV_VAR}: unknown fault point {point!r} "
                f"(valid: {', '.join(POINTS)})")
        arg = 0.0
        if action_s.startswith("delay_ms="):
            action = "delay_ms"
            arg = float(action_s.split("=", 1)[1])
        else:
            action = action_s
        if action not in ACTIONS:
            raise ValueError(
                f"{ENV_VAR}: unknown action {action_s!r} "
                f"(valid: crash, hang, delay_ms=N, econnreset, io_error)")
        # nth=0 = PERSISTENT: fire on EVERY arrival (no one-shot latch) —
        # how a persistently failing disk is modeled (the state plane's
        # bounded retries must exhaust, not be saved by the next attempt).
        if nth < 0:
            raise ValueError(f"{ENV_VAR}: nth must be >= 0, got {nth}")
        return cls(point=point, rank=int(rank_s), action=action, arg=arg,
                   nth=nth)

    @classmethod
    def _parse_serving(cls, text: str) -> "FaultSpec":
        """Expand a serving sugar verb into its serve_forward spec."""
        parts = text.replace("@", ":").split(":")
        verb = parts[0]
        try:
            rank = int(parts[1])
            if rank < 0:
                raise ValueError
        except (IndexError, ValueError):
            raise ValueError(
                f"{ENV_VAR}: {verb} needs a non-negative rank, "
                f"got {text!r}") from None
        if verb == "slow_replica":
            # slow_replica:<rank>:<delay_ms> — persistent (every batch).
            if len(parts) != 3:
                raise ValueError(
                    f"{ENV_VAR}: slow_replica must be "
                    f"slow_replica:<rank>:<delay_ms>, got {text!r}")
            try:
                delay = float(parts[2])
                if delay < 0:
                    raise ValueError
            except ValueError:
                raise ValueError(
                    f"{ENV_VAR}: slow_replica delay_ms must be a "
                    f"non-negative number, got {text!r}") from None
            return cls(point="serve_forward", rank=rank, action="delay_ms",
                       arg=delay, nth=0)
        # replica_crash:<rank>@<nth> / forward_fault:<rank>:<nth>
        # (nth optional, default 1; nth=0 = persistent like the base
        # grammar).
        if len(parts) not in (2, 3):
            raise ValueError(
                f"{ENV_VAR}: {verb} must be {verb}:<rank>[@<nth>], "
                f"got {text!r}")
        try:
            nth = int(parts[2]) if len(parts) == 3 else 1
            if nth < 0:
                raise ValueError
        except ValueError:
            raise ValueError(
                f"{ENV_VAR}: {verb} nth must be >= 0, got {text!r}") \
                from None
        action = "crash" if verb == "replica_crash" else "io_error"
        return cls(point="serve_forward", rank=rank, action=action, nth=nth)


_lock = threading.Lock()
_spec: Optional[FaultSpec] = None
_counts: Dict[str, int] = {}
_fired = False


def _load_env() -> None:
    global _spec
    text = os.environ.get(ENV_VAR)
    if text:
        _spec = FaultSpec.parse(text)


_load_env()


def armed() -> bool:
    """True when a fault directive is armed (env at import, or :func:`arm`).

    Callers on hot paths should cache ``fire`` only when this is True —
    the unarmed fast path then never enters this module at all."""
    return _spec is not None


def spec() -> Optional[FaultSpec]:
    return _spec


def arm(text_or_spec) -> FaultSpec:
    """Arm a fault programmatically (tests); resets the nth-counters."""
    global _spec, _fired
    s = (text_or_spec if isinstance(text_or_spec, FaultSpec)
         else FaultSpec.parse(text_or_spec))
    with _lock:
        _spec = s
        _counts.clear()
        _fired = False
    return s


def disarm() -> None:
    global _spec, _fired
    with _lock:
        _spec = None
        _counts.clear()
        _fired = False


def fired() -> bool:
    """True once the armed fault has executed (tests assert determinism)."""
    return _fired


def fire(point: str, rank: int,
         sever: Optional[Callable[[], None]] = None) -> None:
    """Arrive at a named fault point; executes the armed action when this
    is the spec'd (point, rank) and the spec'd nth arrival.

    ``sever`` is the caller-supplied connection killer for ``econnreset``
    (the socket lives behind the native library, so only the caller can
    reach it); a point with no sever degrades to a logged no-op rather
    than a surprise crash."""
    global _fired
    s = _spec
    if s is None or s.point != point or s.rank != rank:
        return
    with _lock:
        n = _counts.get(point, 0) + 1
        _counts[point] = n
        if s.nth == 0:
            _fired = True           # persistent: every arrival executes
        else:
            if n != s.nth or _fired:
                return
            _fired = True
    log.warning("fault injection: %s at %s (rank %d, arrival %d)",
                s.action, point, rank, n)
    if s.action == "crash":
        # Unclean death, bypassing atexit/finally — the honest simulation
        # of a SIGKILL'd / OOM'd worker.  Flush what the test harness may
        # be tailing first.
        import sys
        try:
            sys.stdout.flush()
            sys.stderr.flush()
        except Exception:  # noqa: BLE001 - exiting anyway
            pass
        os._exit(_EXIT_CODE)
    elif s.action == "hang":
        time.sleep(_HANG_S)
    elif s.action == "delay_ms":
        time.sleep(s.arg / 1000.0)
    elif s.action == "econnreset":
        if sever is None:
            log.warning("fault injection: econnreset at %s has no sever "
                        "callback; ignoring", point)
        else:
            sever()
    elif s.action == "io_error":
        # Raised INTO the caller: the state plane's chunk writer (and any
        # future I/O fault point) sees exactly what a failing filesystem
        # would hand it — an OSError from the write path.
        raise OSError(f"injected I/O fault at {point} (HVD_TPU_FAULT)")


# --------------------------------------------------------------- churn verbs
# Scheduled CHURN events (ISSUE 12): where the fault points above inject a
# single failure at a protocol point, a churn SCRIPT replays membership
# change — clean LEAVEs, join epochs, agent death, preemption notices —
# against a running control plane.  The script grammar is round-gated like
# the fault points' nth gate::
#
#     HVD_TPU_CHURN=<verb>:<target>@<round>[,<verb>:<target>@<round>...]
#
#     verb    leave           the target RANK sends a protocol-v6 clean
#                             LEAVE in place of its round frame and departs
#             join            the target RANK (or ``*`` = every live rank)
#                             announces the join protocol ("\x1f__join__"),
#                             flushing the response-cache slot table — the
#                             heavyweight control-plane churn event
#             agent_crash     the target HOST's per-host agent is killed
#                             abruptly (survivable only once its ranks have
#                             left; otherwise a host-granular typed abort)
#             preempt_notice  the target HOST receives a preemption notice:
#                             the runner drains it — every live rank of the
#                             host leaves cleanly (the driver's DRAIN →
#                             clean LEAVE path, compressed to the wire)
#     target  a rank id (leave/join), ``*`` (join: all live ranks), or a
#             host index (agent_crash/preempt_notice)
#     round   the 1-based negotiation round the event fires BEFORE —
#             events at round N are applied once the fleet has completed
#             N-1 measured rounds, so a ``leave`` is the target's round-N
#             frame (deterministic, like the fault points' nth gate)
#
# The scripts are replayed by :class:`horovod_tpu.testing.churn.ChurnRunner`
# against the REAL native server, flat or hierarchical.

#     verb    rejoin_restore  (ISSUE 14) the target RANK — which must
#                             have departed in an earlier event — rejoins
#                             the STATE plane as a fresh replacement: its
#                             state plane is reset and restored from the
#                             survivors' shard servers (peer path) or the
#                             shared manifest directory (disk fallback);
#                             the runner records the restore source
#                             ("peer"/"disk"), epoch and disk-read count
#                             in the phase/event output so scenarios can
#                             assert WHICH path recovery took
CHURN_ENV_VAR = "HVD_TPU_CHURN"
CHURN_VERBS = ("leave", "join", "agent_crash", "preempt_notice",
               "rejoin_restore")
_HOST_VERBS = ("agent_crash", "preempt_notice")


@dataclasses.dataclass(frozen=True)
class ChurnEvent:
    """One parsed churn-script event."""
    verb: str
    target: str     # rank id, "*" (join only), or host index
    at_round: int   # fires before this 1-based measured round

    @classmethod
    def parse(cls, text: str) -> "ChurnEvent":
        head, sep, round_s = text.strip().partition("@")
        if not sep:
            raise ValueError(
                f"{CHURN_ENV_VAR}: event must be <verb>:<target>@<round>, "
                f"got {text!r}")
        parts = head.split(":")
        if len(parts) != 2:
            raise ValueError(
                f"{CHURN_ENV_VAR}: event must be <verb>:<target>@<round>, "
                f"got {text!r}")
        verb, target = parts[0].strip(), parts[1].strip()
        if verb not in CHURN_VERBS:
            raise ValueError(
                f"{CHURN_ENV_VAR}: unknown churn verb {verb!r} "
                f"(valid: {', '.join(CHURN_VERBS)})")
        if target == "*":
            if verb != "join":
                raise ValueError(
                    f"{CHURN_ENV_VAR}: target '*' is only valid for join, "
                    f"got {text!r}")
        else:
            try:
                if int(target) < 0:
                    raise ValueError
            except ValueError:
                raise ValueError(
                    f"{CHURN_ENV_VAR}: target must be a non-negative "
                    f"{'host index' if verb in _HOST_VERBS else 'rank'} "
                    f"or '*', got {text!r}") from None
        try:
            at_round = int(round_s)
        except ValueError:
            raise ValueError(
                f"{CHURN_ENV_VAR}: round must be an integer, got "
                f"{text!r}") from None
        if at_round < 1:
            raise ValueError(
                f"{CHURN_ENV_VAR}: round must be >= 1, got {text!r}")
        return cls(verb=verb, target=target, at_round=at_round)


def parse_churn(text: str):
    """Parse a full churn script (comma-separated events) into a list of
    :class:`ChurnEvent`, ordered by firing round (stable for ties — the
    written order breaks them, so ``leave:1@5,join:*@5`` leaves first)."""
    events = [ChurnEvent.parse(p) for p in (text or "").split(",")
              if p.strip()]
    return sorted(events, key=lambda e: e.at_round)
