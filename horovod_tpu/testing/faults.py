"""Deterministic fault-injection harness for the coordinator control plane.

The reference proves its failure paths with integration tests that really
kill workers (``test/integration/test_elastic_torch.py`` SIGKILLs a rank
mid-epoch); reproducing that deterministically needs the kill to land at a
*named protocol point*, not "roughly when the signal arrives".  This
module provides those points: the controller (and anything else on the
control plane) calls :func:`fire` at well-known places, and a single
environment variable arms exactly one of them::

    HVD_TPU_FAULT=<point>:<rank>:<action>[:<nth>]

    point   connect        before the TCP connect to the coordinator
            pre_announce   entering negotiate(), before building announces
            round_send     before the request frame is written
            mid_round_exit after the request is sent, before the response
                           is read (a crash here is the classic
                           "died mid-negotiation" shape: the server has
                           this rank's frame, the rank is gone)
            round_recv     before blocking for the response frame
    rank    the rank the fault targets (other ranks never fire)
    action  crash          os._exit(13) — an unclean process death
            hang           sleep forever (bounded by _HANG_S; trips round
                           deadlines / stall machinery)
            delay_ms=N     sleep N milliseconds, then continue
            econnreset     abruptly sever the controller connection (the
                           caller passes the sever callback), then
                           continue — the peer observes a dead socket
    nth     fire on the nth arrival at that point (default 1); earlier
            arrivals pass through untouched, later ones too (one-shot)

Zero-cost when unarmed: :func:`armed` is a module-flag check, and the
controller caches ``fire`` only when it returns True — an unarmed run
never executes a single instruction of this module on the hot path (the
steady-state frame guard in ``tests/test_response_cache.py`` additionally
proves the wire carries zero extra bytes either way).

No jax imports (tier-1 purity guard).  Thread-safe: the nth-counters are
lock-guarded because fault points fire from the engine cycle thread while
tests may arm/disarm from the main thread.
"""

from __future__ import annotations

import dataclasses
import os
import threading
import time
from typing import Callable, Dict, Optional

from ..utils.logging import get_logger

log = get_logger()

ENV_VAR = "HVD_TPU_FAULT"

POINTS = ("connect", "pre_announce", "round_send", "mid_round_exit",
          "round_recv")
ACTIONS = ("crash", "hang", "delay_ms", "econnreset")

# Bounded "forever": long enough to trip any reasonable deadline, short
# enough that a leaked daemon thread cannot outlive a CI job by much.
_HANG_S = 3600.0

_EXIT_CODE = 13  # distinct from rc=1 so tests can tell crash from bug


@dataclasses.dataclass(frozen=True)
class FaultSpec:
    """One parsed ``HVD_TPU_FAULT`` directive."""
    point: str
    rank: int
    action: str
    arg: float = 0.0     # delay_ms payload
    nth: int = 1

    @classmethod
    def parse(cls, text: str) -> "FaultSpec":
        parts = text.strip().split(":")
        if len(parts) not in (3, 4):
            raise ValueError(
                f"{ENV_VAR} must be <point>:<rank>:<action>[:<nth>], "
                f"got {text!r}")
        point, rank_s, action_s = parts[0], parts[1], parts[2]
        nth = int(parts[3]) if len(parts) == 4 else 1
        if point not in POINTS:
            raise ValueError(
                f"{ENV_VAR}: unknown fault point {point!r} "
                f"(valid: {', '.join(POINTS)})")
        arg = 0.0
        if action_s.startswith("delay_ms="):
            action = "delay_ms"
            arg = float(action_s.split("=", 1)[1])
        else:
            action = action_s
        if action not in ACTIONS:
            raise ValueError(
                f"{ENV_VAR}: unknown action {action_s!r} "
                f"(valid: crash, hang, delay_ms=N, econnreset)")
        if nth < 1:
            raise ValueError(f"{ENV_VAR}: nth must be >= 1, got {nth}")
        return cls(point=point, rank=int(rank_s), action=action, arg=arg,
                   nth=nth)


_lock = threading.Lock()
_spec: Optional[FaultSpec] = None
_counts: Dict[str, int] = {}
_fired = False


def _load_env() -> None:
    global _spec
    text = os.environ.get(ENV_VAR)
    if text:
        _spec = FaultSpec.parse(text)


_load_env()


def armed() -> bool:
    """True when a fault directive is armed (env at import, or :func:`arm`).

    Callers on hot paths should cache ``fire`` only when this is True —
    the unarmed fast path then never enters this module at all."""
    return _spec is not None


def spec() -> Optional[FaultSpec]:
    return _spec


def arm(text_or_spec) -> FaultSpec:
    """Arm a fault programmatically (tests); resets the nth-counters."""
    global _spec, _fired
    s = (text_or_spec if isinstance(text_or_spec, FaultSpec)
         else FaultSpec.parse(text_or_spec))
    with _lock:
        _spec = s
        _counts.clear()
        _fired = False
    return s


def disarm() -> None:
    global _spec, _fired
    with _lock:
        _spec = None
        _counts.clear()
        _fired = False


def fired() -> bool:
    """True once the armed fault has executed (tests assert determinism)."""
    return _fired


def fire(point: str, rank: int,
         sever: Optional[Callable[[], None]] = None) -> None:
    """Arrive at a named fault point; executes the armed action when this
    is the spec'd (point, rank) and the spec'd nth arrival.

    ``sever`` is the caller-supplied connection killer for ``econnreset``
    (the socket lives behind the native library, so only the caller can
    reach it); a point with no sever degrades to a logged no-op rather
    than a surprise crash."""
    global _fired
    s = _spec
    if s is None or s.point != point or s.rank != rank:
        return
    with _lock:
        n = _counts.get(point, 0) + 1
        _counts[point] = n
        if n != s.nth or _fired:
            return
        _fired = True
    log.warning("fault injection: %s at %s (rank %d, arrival %d)",
                s.action, point, rank, n)
    if s.action == "crash":
        # Unclean death, bypassing atexit/finally — the honest simulation
        # of a SIGKILL'd / OOM'd worker.  Flush what the test harness may
        # be tailing first.
        import sys
        try:
            sys.stdout.flush()
            sys.stderr.flush()
        except Exception:  # noqa: BLE001 - exiting anyway
            pass
        os._exit(_EXIT_CODE)
    elif s.action == "hang":
        time.sleep(_HANG_S)
    elif s.action == "delay_ms":
        time.sleep(s.arg / 1000.0)
    elif s.action == "econnreset":
        if sever is None:
            log.warning("fault injection: econnreset at %s has no sever "
                        "callback; ignoring", point)
        else:
            sever()
