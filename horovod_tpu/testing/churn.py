"""Churn-scenario runner: scripted membership change against the REAL
native control plane (ISSUE 12, jax-free).

Where :mod:`horovod_tpu.testing.faults` injects ONE failure at a named
protocol point, this module replays a whole churn SCRIPT — clean LEAVEs,
join epochs, agent death, preemption-notice drains
(:func:`~.faults.parse_churn` grammar) — against a running
``csrc/coordinator.cc`` root, flat (one connection per rank) or
hierarchical (ranks behind real per-host
:class:`~..common.host_agent.HostAgent` aggregators).  The simulated
ranks speak raw warm-path frames (the steady-state floor: no full
announces, empty bitvector, no tags), so what is measured is pure
control-plane service — the same world the ``negotiation_scaling`` bench
drives, now with churn injected mid-run.

Execution model: the measured rounds are split into PHASES at each
scripted event's round.  Rank threads free-run the rounds inside a phase
(lock-step with the server, no artificial gates on the hot path); between
phases the main thread applies the due events deterministically — marks
leavers/joiners (their next round frame is the LEAVE / join announce),
kills agents, expands a preemption notice into the host's drain.  Every
phase reports its own wall-per-round and the root's own service time
(``hvdtpu_server_stats`` deltas), so a slope can be read ACROSS the churn,
not just before it.

A typed ABORT (or an unexplained sever) observed by any rank ends the run
with ``survived=False`` and the abort's attribution — which is itself a
valid scenario outcome: ``agent_crash`` on a host with live ranks is
DEFINED to abort with host-granular attribution, and the tests pin both
directions.
"""

from __future__ import annotations

import ctypes
import socket
import struct
import threading
import time
from typing import Dict, List, Optional, Sequence

from .faults import ChurnEvent, _HOST_VERBS
from ..utils.logging import get_logger

log = get_logger()

_LEAVE_WIRE = struct.pack("<I", 8) + struct.pack(
    "<II", 0xFFFFFFFE, 0x3645564C)
_ABORT_ESCAPE = 0xFFFFFFFF

# The 12-byte steady-state warm frame: n_full=0, empty bitvector, n_tag=0.
_WARM_PAYLOAD = struct.pack("<III", 0, 0, 0)
_WARM_WIRE = struct.pack("<I", len(_WARM_PAYLOAD)) + _WARM_PAYLOAD
# Round-1 frame: the warm core plus the LVE6 + FLT1 capability ads (the
# client contract keeps FLT1 LAST — the server's abort-path salvage reads
# the final 8 bytes).  Without the LVE6 ad the server would IGNORE every
# scripted LEAVE (it only honors one when all survivors latched v6) and
# the leaver's socket close would sever the fleet.
_CAP_PAYLOAD = (_WARM_PAYLOAD
                + struct.pack("<II", 0x3645564C, 0)      # LVE6 ad
                + struct.pack("<II", 0x31544C46, 0))     # FLT1 ad
_CAP_WIRE = struct.pack("<I", len(_CAP_PAYLOAD)) + _CAP_PAYLOAD


def _join_wire() -> bytes:
    """A full-announce frame carrying only the reserved join name."""
    payload = struct.pack("<I", 1)       # n_announce
    payload += struct.pack("<H", 0)      # required (0 = world)
    for field in (b"\x1f__join__", b"", b"-1", b"-1", b""):
        payload += struct.pack("<H", len(field)) + field
    payload += struct.pack("<II", 0, 0)  # empty bitvector + n_tag
    return struct.pack("<I", len(payload)) + payload


_JOIN_WIRE = _join_wire()


def _read_frame(sock: socket.socket) -> Optional[bytes]:
    buf = b""
    while len(buf) < 4:
        c = sock.recv(4 - len(buf))
        if not c:
            return None
        buf += c
    (n,) = struct.unpack("<I", buf)
    data = b""
    while len(data) < n:
        c = sock.recv(min(n - len(data), 65536))
        if not c:
            return None
        data += c
    return data


class ChurnRunner:
    """Replay one churn script against a fresh native root server.

    ``world`` simulated ranks, grouped ``ranks_per_host`` to a host
    (hosts are the targets of the host verbs; required whenever the
    script names one).  ``hier=True`` puts a real :class:`HostAgent` in
    front of every host's ranks — the scale-out control plane under
    churn.  ``rounds`` measured rounds after ``warm`` warmup rounds;
    events' ``at_round`` index into the measured range.
    """

    def __init__(self, world: int, ranks_per_host: int = 0,
                 hier: bool = False, rounds: int = 30, warm: int = 5,
                 script: Sequence[ChurnEvent] = (),
                 connect_timeout_ms: int = 30000,
                 round_deadline_ms: int = 0,
                 state_dir: Optional[str] = None,
                 serve_state: bool = True):
        if world < 2:
            raise ValueError("ChurnRunner needs world >= 2")
        if hier and ranks_per_host <= 0:
            raise ValueError("hier=True needs ranks_per_host > 0")
        self.world = int(world)
        self.hier = bool(hier)
        self.rounds = int(rounds)
        # At least one warm round: it carries the LVE6/FLT1 capability
        # ads, without which the server degrades every LEAVE to a sever.
        self.warm = max(1, int(warm))
        self.script = sorted(script, key=lambda e: e.at_round)
        self.connect_timeout_ms = int(connect_timeout_ms)
        self.round_deadline_ms = int(round_deadline_ms)
        rph = int(ranks_per_host) if ranks_per_host else 0
        if any(e.verb in _HOST_VERBS for e in self.script) and rph <= 0:
            raise ValueError("host-targeted churn verbs need ranks_per_host")
        self.hosts: List[List[int]] = (
            [list(range(i, min(world, i + rph)))
             for i in range(0, world, rph)] if rph > 0 else
            [[r] for r in range(world)])
        for e in self.script:
            if e.at_round > self.rounds:
                raise ValueError(
                    f"churn event {e} beyond the run ({self.rounds} rounds)")
            if e.verb in _HOST_VERBS and int(e.target) >= len(self.hosts):
                raise ValueError(f"churn event {e}: no host {e.target}")
            if e.verb in ("leave", "rejoin_restore") \
                    and int(e.target) >= world:
                raise ValueError(f"churn event {e}: no rank {e.target}")
            if e.verb == "agent_crash" and not self.hier:
                raise ValueError("agent_crash needs hier=True (no agents "
                                 "exist on the flat plane)")
        # Resilient state plane (ISSUE 14): rejoin_restore replays a
        # replacement rank's state recovery against the survivors' shard
        # servers / the shared manifest directory.  The target must have
        # departed in an EARLIER event, or there is nothing to rejoin.
        self._needs_state = any(e.verb == "rejoin_restore"
                                for e in self.script)
        for e in self.script:
            if e.verb != "rejoin_restore":
                continue
            r = int(e.target)
            departed = any(
                (p.verb == "leave" and int(p.target) == r)
                or (p.verb == "preempt_notice"
                    and r in self.hosts[int(p.target)])
                for p in self.script if p.at_round < e.at_round)
            if not departed:
                raise ValueError(
                    f"churn event {e}: rank {r} never departed before "
                    f"its rejoin_restore (add a leave/preempt first)")
        self.state_dir = state_dir
        self.serve_state = bool(serve_state)
        self._planes: List = []
        # Phases: [warm] + measured segments split at each event round.
        bounds = sorted({e.at_round for e in self.script})
        self._phases: List[dict] = []
        if self.warm:
            self._phases.append({"rounds": self.warm, "events": [],
                                 "measured": False})
        prev = 1
        for b in bounds:
            if b > prev:
                self._phases.append({"rounds": b - prev, "events": [],
                                     "measured": True})
            self._phases.append(
                {"rounds": 0, "measured": True,
                 "events": [e for e in self.script if e.at_round == b]})
            prev = b
        if self.rounds + 1 > prev:
            self._phases.append({"rounds": self.rounds + 1 - prev,
                                 "events": [], "measured": True})
        # Merge each zero-round event marker into the phase that follows
        # it (events fire BEFORE that phase's first round).
        merged: List[dict] = []
        pending_events: List[ChurnEvent] = []
        for ph in self._phases:
            if ph["rounds"] == 0:
                pending_events.extend(ph["events"])
                continue
            ph["events"] = pending_events + ph["events"]
            pending_events = []
            merged.append(ph)
        if pending_events:
            # Events scheduled after the final round: give them a
            # zero-length tail phase is meaningless — fire after last
            # phase instead (recorded, mostly for leave-at-end scripts).
            merged.append({"rounds": 1, "events": pending_events,
                           "measured": True})
        self._phases = merged

        # Runtime state.
        self._directives: List[Dict[int, str]] = [
            {} for _ in self._phases]
        self._go = [threading.Event() for _ in self._phases]
        self._done_lock = threading.Lock()
        self._done_count = [0] * len(self._phases)
        self._done_cv = threading.Condition(self._done_lock)
        self._abort = threading.Event()
        self._stop = threading.Event()
        self._left: set = set()
        self._dead: set = set()
        self.failures: List[tuple] = []
        self.abort_reason: Optional[str] = None
        self.events_fired: List[dict] = []
        self.drained_hosts: List[int] = []
        # State-plane runtime (rejoin_restore scripts only).
        self._state_left: set = set()
        self._state_epoch = 0
        self.restores: List[dict] = []

    # ------------------------------------------------------------- threads
    def _done(self, phase: int) -> None:
        with self._done_cv:
            self._done_count[phase] += 1
            self._done_cv.notify_all()

    def _fail(self, rank: int, why: str, abort: bool = False) -> None:
        self.failures.append((rank, why))
        self._dead.add(rank)
        if abort and not self._abort.is_set():
            self.abort_reason = self.abort_reason or why
            self._abort.set()

    def _rank_loop(self, rank: int, connect_port: int) -> None:
        sock = None
        try:
            deadline = time.monotonic() + self.connect_timeout_ms / 1000.0
            while time.monotonic() < deadline and not self._stop.is_set():
                try:
                    sock = socket.create_connection(
                        ("127.0.0.1", connect_port), timeout=5)
                    break
                except OSError:
                    time.sleep(0.02)
            if sock is None:
                self._fail(rank, "never connected", abort=True)
                return
            sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
            sock.sendall(struct.pack("<I", rank))
            first_send = True
            for p, phase in enumerate(self._phases):
                if not self._go[p].wait(timeout=120):
                    self._fail(rank, f"phase {p} gate timeout", abort=True)
                    return
                if self._stop.is_set() or self._abort.is_set():
                    return
                d = self._directives[p].get(rank, "")
                if d == "leave":
                    # The LEAVE is this rank's round frame for the phase's
                    # first round; no response is owed to a leaver.  The
                    # brief linger lets the frame land before the EOF.
                    sock.sendall(_LEAVE_WIRE)
                    self._left.add(rank)
                    time.sleep(0.05)
                    sock.close()
                    sock = None
                    self._done(p)
                    return
                for i in range(phase["rounds"]):
                    if i == 0 and d == "join":
                        wire = _JOIN_WIRE
                    elif first_send:
                        wire = _CAP_WIRE
                    else:
                        wire = _WARM_WIRE
                    first_send = False
                    sock.sendall(wire)
                    resp = _read_frame(sock)
                    if resp is None:
                        self._fail(rank, "severed by the control plane",
                                   abort=True)
                        self._done(p)
                        return
                    if len(resp) >= 4 and struct.unpack_from(
                            "<I", resp)[0] == _ABORT_ESCAPE:
                        self._fail(rank, f"typed abort: {resp[8:64]!r}",
                                   abort=True)
                        self._done(p)
                        return
                self._done(p)
        except OSError as exc:
            self._fail(rank, repr(exc), abort=True)
            with self._done_cv:
                self._done_cv.notify_all()
        finally:
            if sock is not None:
                try:
                    sock.close()
                except OSError:
                    pass

    # --------------------------------------------------------- state plane
    def _synthetic_state(self, epoch: int) -> dict:
        """Deterministic per-epoch state every live rank holds identically
        (the bitwise-restore assertion compares against exactly this).

        Includes a sharded-optimizer saveable (ISSUE 15) in the exact
        rank-invariant marker form ``JaxState.save`` emits for a
        ``DistributedOptimizer(sharded=True)`` state, so rejoin_restore
        also proves a re-joiner re-slices exactly its own 1/N optimizer
        shard from the recovered commit."""
        import numpy as np
        return {"step": epoch,
                "params": (np.arange(512, dtype=np.float32)
                           * float(epoch)),
                "opt": {"__hvd_sharded_opt__": 1, "world": self.world,
                        "plan": {},
                        "inner_states": [
                            {"mu": np.arange(self.world * 64,
                                             dtype=np.float32)
                             + float(epoch),
                             "count": np.int32(epoch)}]}}

    def _state_setup(self) -> None:
        import tempfile

        from ..elastic.stateplane import StatePlane
        if self.state_dir is None:
            self.state_dir = tempfile.mkdtemp(prefix="hvd_churn_state_")
        self._planes = [StatePlane(self.state_dir, rank=r, world=self.world,
                                   serve=self.serve_state)
                        for r in range(self.world)]
        self._advance_state_epoch()          # epoch 1: the disk baseline

    def _advance_state_epoch(self) -> None:
        """Every live rank commits the next epoch (inline durable write;
        the wire fleet is untouched) — the survivors' state moving on
        past a departure, which is what makes a later rejoiner's PEER
        path strictly newer than its own last epoch.  Survivors re-shard
        over the SHRUNK world, exactly like the real re-rendezvous
        (elastic_bootstrap re-assigns rank/world): without it, every
        post-departure epoch would be missing the leaver's shard and
        never complete on disk."""
        self._state_epoch += 1
        state = self._synthetic_state(self._state_epoch)
        live = [r for r, plane in enumerate(self._planes)
                if plane is not None and r not in self._state_left
                and r not in self._dead]
        for i, r in enumerate(live):
            plane = self._planes[r]
            plane.rank, plane.world = i, len(live)
            plane.commit(state=state, epoch=self._state_epoch)

    def _state_depart(self, rank: int) -> None:
        if not self._planes:
            return
        self._state_left.add(rank)
        plane = self._planes[rank]
        if plane is not None:
            plane.close()        # a departed rank serves no shards

    def _rejoin_restore(self, rank: int) -> dict:
        """A fresh replacement rank's state recovery: reset the plane
        (epoch -1, empty memory — a new process knows nothing) and
        restore peer-first from the live survivors' shard servers, disk
        manifest as the fallback.  Returns the assertion record."""
        from ..elastic.stateplane import StatePlane
        old = self._planes[rank]
        if old is not None:
            old.close()
        plane = StatePlane(self.state_dir, rank=rank, world=self.world,
                           serve=self.serve_state)
        self._planes[rank] = plane
        peers = [("127.0.0.1", p.server.port)
                 for i, p in enumerate(self._planes)
                 if p is not None and i != rank and p.server is not None
                 and i not in self._state_left and i not in self._dead]
        try:
            data, epoch, source = plane.restore(peers=peers)
            rec = {"restore_source": source, "restore_epoch": epoch,
                   "disk_reads": plane.disk_reads,
                   "peer_shards": plane.peer_shards_fetched}
            # Shard-native optimizer restore (ISSUE 15): the recovered
            # sharded-optimizer saveable must yield exactly this rank's
            # own 1/N slice under the pad+slice convention.
            opt = data.get("opt") if isinstance(data, dict) else None
            if isinstance(opt, dict) and opt.get("__hvd_sharded_opt__"):
                import numpy as np

                from ..elastic.stateplane import shard_slice_array
                full = np.asarray(opt["inner_states"][0]["mu"])
                got = shard_slice_array(full, rank, int(opt["world"]))
                want = np.arange(self.world * 64, dtype=np.float32)
                want = want + float(epoch)
                per = want.size // int(opt["world"])
                rec["opt_shard_ok"] = bool(
                    np.array_equal(got, want[rank * per:(rank + 1) * per]))
                rec["opt_shard_len"] = int(got.size)
        except FileNotFoundError as exc:
            rec = {"restore_source": None, "restore_error": str(exc)}
        else:
            self._state_left.discard(rank)
        self.restores.append(dict(rec, rank=rank))
        return rec

    # -------------------------------------------------------------- events
    def _apply_events(self, phase_idx: int, events: List[ChurnEvent],
                      agents: list) -> None:
        directives = self._directives[phase_idx]
        for e in events:
            rec = {"verb": e.verb, "target": e.target,
                   "at_round": e.at_round}
            if e.verb == "leave":
                r = int(e.target)
                if r not in self._left and r not in self._dead:
                    directives[r] = "leave"
                    self._state_depart(r)
                    if self._planes:
                        self._advance_state_epoch()
            elif e.verb == "rejoin_restore":
                rec.update(self._rejoin_restore(int(e.target)))
            elif e.verb == "join":
                targets = ([int(e.target)] if e.target != "*" else
                           [r for r in range(self.world)
                            if r not in self._left and r not in self._dead])
                for r in targets:
                    if directives.get(r) != "leave":
                        directives[r] = "join"
                rec["ranks"] = targets
            elif e.verb == "preempt_notice":
                # The driver's DRAIN → clean LEAVE path, compressed to the
                # wire: every live rank of the host departs this phase.
                h = int(e.target)
                self.drained_hosts.append(h)
                drained = []
                for r in self.hosts[h]:
                    if r not in self._left and r not in self._dead:
                        directives[r] = "leave"
                        drained.append(r)
                        self._state_depart(r)
                if drained and self._planes:
                    self._advance_state_epoch()
                rec["ranks"] = drained
            elif e.verb == "agent_crash":
                h = int(e.target)
                if agents and h < len(agents):
                    agents[h].kill()
                    rec["live_ranks"] = [
                        r for r in self.hosts[h]
                        if r not in self._left and r not in self._dead]
            self.events_fired.append(rec)

    # ----------------------------------------------------------------- run
    def run(self) -> dict:
        from ..common.host_agent import HostAgent
        from ..common.native import load as _load
        from ..common.net import free_ports

        lib = _load()
        if self._needs_state and not self._planes:
            self._state_setup()
        (port,) = free_ports(1)
        server = lib.hvdtpu_server_start(
            port, self.world, ctypes.c_double(600.0), 2048,
            self.round_deadline_ms, 0, 0)
        if not server:
            raise RuntimeError(f"churn server failed to start on {port}")
        agents: List[HostAgent] = []
        connect_port = {r: port for r in range(self.world)}
        if self.hier:
            agents = [HostAgent(0, "127.0.0.1", port, ranks, host_index=j,
                                connect_timeout_ms=self.connect_timeout_ms
                                ).start()
                      for j, ranks in enumerate(self.hosts)]
            for a, ranks in zip(agents, self.hosts):
                for r in ranks:
                    connect_port[r] = a.port
        threads = [threading.Thread(target=self._rank_loop,
                                    args=(r, connect_port[r]), daemon=True)
                   for r in range(self.world)]
        stats = (ctypes.c_double * 2)()

        def server_totals():
            """(rounds_served, total_service_us) — per-phase deltas give
            the root's own service time across the churn."""
            if lib.hvdtpu_server_stats(server, stats) != 0:
                return 0.0, 0.0
            return float(stats[0]), float(stats[0]) * float(stats[1])

        phase_reports: List[dict] = []
        try:
            for t in threads:
                t.start()
            for p, phase in enumerate(self._phases):
                if self._abort.is_set():
                    break
                self._apply_events(p, phase["events"], agents)
                # Leavers count as participants: they play the phase's
                # first round (their LEAVE frame) and signal done.
                live = [r for r in range(self.world)
                        if r not in self._left and r not in self._dead]
                participants = len(live)
                if participants <= 1:
                    break   # a 1-rank fleet has nothing to negotiate with
                r0, ns0 = server_totals()
                t0 = time.perf_counter()
                self._go[p].set()
                deadline = time.monotonic() + 120
                with self._done_cv:
                    while (self._done_count[p] < participants
                           and not self._abort.is_set()):
                        if time.monotonic() > deadline:
                            self.abort_reason = (self.abort_reason
                                                 or f"phase {p} timed out")
                            self._abort.set()
                            break
                        self._done_cv.wait(timeout=0.5)
                wall = time.perf_counter() - t0
                r1, ns1 = server_totals()
                if phase["measured"] and phase["rounds"] > 0 \
                        and not self._abort.is_set():
                    phase_reports.append({
                        "rounds": phase["rounds"],
                        "live_ranks": participants,
                        "wall_us_per_round": round(
                            wall / phase["rounds"] * 1e6, 1),
                        "root_us": round((ns1 - ns0) / (r1 - r0), 1)
                        if r1 > r0 else None,
                    })
        finally:
            self._stop.set()
            self._abort.set()         # release any rank blocked in a gate
            for ev in self._go:
                ev.set()
            for t in threads:
                t.join(timeout=15)
            for a in agents:
                try:
                    a.stop()
                except Exception:  # noqa: BLE001 - teardown best-effort
                    pass
            for p in self._planes:
                try:
                    if p is not None:
                        p.close()
                except Exception:  # noqa: BLE001 - teardown best-effort
                    pass
            lib.hvdtpu_server_stop(server)
        survived = self.abort_reason is None
        measured = [ph for ph in phase_reports if ph["root_us"] is not None]
        return {
            "world": self.world,
            "hier": self.hier,
            "hosts": len(self.hosts),
            "rounds": self.rounds,
            "survived": survived,
            "abort_reason": self.abort_reason,
            "left_ranks": sorted(self._left),
            "drained_hosts": sorted(set(self.drained_hosts)),
            "restores": self.restores,
            "state_epoch": self._state_epoch if self._planes else None,
            "events_fired": self.events_fired,
            "failures": self.failures[:8],
            "phases": phase_reports,
            "root_us_pre": measured[0]["root_us"] if measured else None,
            "root_us_post": measured[-1]["root_us"] if measured else None,
            "wall_us_per_round": round(
                sum(ph["wall_us_per_round"] * ph["rounds"]
                    for ph in phase_reports)
                / max(1, sum(ph["rounds"] for ph in phase_reports)), 1)
            if phase_reports else None,
            "root_us": round(
                sum((ph["root_us"] or 0.0) * ph["rounds"] for ph in measured)
                / max(1, sum(ph["rounds"] for ph in measured)), 1)
            if measured else None,
        }
