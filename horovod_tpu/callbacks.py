"""Training-loop callbacks for JAX training loops.

Parity: the reference Keras callbacks (``horovod/_keras/callbacks.py`` —
SURVEY.md §2b P5): ``BroadcastGlobalVariablesCallback``,
``MetricAverageCallback``, ``LearningRateWarmupCallback``,
``LearningRateScheduleCallback``.

TPU-first design: the learning-rate policies are ALSO exposed as optax
schedules (``warmup_scaled_schedule``) — inside a jitted train step a
schedule is compiler-visible and free, which is the idiomatic home for the
"scale LR by size(), warm up for N epochs" recipe the reference implements
by mutating ``optimizer.lr`` between epochs.  The callback classes drive the
same policies for imperative loops and match the reference surface.
"""

from __future__ import annotations

import math
from typing import Any, Callable, Dict, Optional

import jax
import numpy as np

from .common import basics
from .ops import eager
from .ops import collectives as C


class Callback:
    """Minimal hook protocol (a structural subset of keras.Callback)."""

    def on_train_begin(self, state: Any = None):
        pass

    def on_epoch_begin(self, epoch: int, state: Any = None):
        pass

    def on_epoch_end(self, epoch: int, state: Any = None,
                     metrics: Optional[Dict[str, float]] = None):
        pass

    def on_batch_end(self, batch: int, state: Any = None):
        pass


class BroadcastGlobalVariablesCallback(Callback):
    """Broadcast rank 0's parameters to all ranks at train start.

    Reference: ``BroadcastGlobalVariablesCallback`` — ensures consistent
    initialization (or checkpoint-restored state) across ranks.  ``state``
    must expose ``params`` (a pytree); ``opt_state`` is broadcast too when
    present.
    """

    def __init__(self, root_rank: int = 0):
        self.root_rank = root_rank

    def on_train_begin(self, state: Any = None):
        if state is None or basics.size() <= 1:
            return
        state.params = broadcast_pytree(state.params, self.root_rank)
        if getattr(state, "opt_state", None) is not None:
            state.opt_state = broadcast_pytree(state.opt_state,
                                               self.root_rank)


# The shared implementation lives in ops/eager.py; re-exported here because
# callback users reach for it alongside BroadcastGlobalVariablesCallback.
broadcast_pytree = eager.broadcast_pytree


class MetricAverageCallback(Callback):
    """Average epoch metrics over all ranks (reference:
    ``MetricAverageCallback``) so logged values reflect the global job."""

    def on_epoch_end(self, epoch: int, state: Any = None,
                     metrics: Optional[Dict[str, float]] = None):
        if not metrics or basics.size() <= 1:
            return
        keys = sorted(k for k, v in metrics.items()
                      if isinstance(v, (int, float, np.floating, np.integer)))
        if not keys:
            return
        vec = np.asarray([float(metrics[k]) for k in keys], np.float32)
        out = eager.to_local(eager.allreduce(
            vec if eager.per_process_mode() else eager.replicated(vec),
            name=f"metric_avg.{epoch}", op=C.ReduceOp.AVERAGE))
        for k, v in zip(keys, np.asarray(out).reshape(-1)):
            metrics[k] = float(v)


class LearningRateScheduleCallback(Callback):
    """Multiply the base LR by ``multiplier(epoch)`` within [start_epoch,
    end_epoch) (reference: ``LearningRateScheduleCallback``).  ``state``
    must expose an ``lr`` attribute consumed by the train step."""

    def __init__(self, initial_lr: float, multiplier, start_epoch: int = 0,
                 end_epoch: Optional[int] = None, staircase: bool = True):
        self.initial_lr = initial_lr
        self.start_epoch = start_epoch
        self.end_epoch = end_epoch
        self.staircase = staircase
        if callable(multiplier):
            self.multiplier = multiplier
        else:
            self.multiplier = lambda epoch: multiplier

    def _in_range(self, epoch: int) -> bool:
        return epoch >= self.start_epoch and (
            self.end_epoch is None or epoch < self.end_epoch)

    def on_epoch_begin(self, epoch: int, state: Any = None):
        if state is not None and self._in_range(epoch):
            state.lr = self.initial_lr * self.multiplier(epoch)


class LearningRateWarmupCallback(LearningRateScheduleCallback):
    """Gradual LR warmup to ``initial_lr * size()`` over ``warmup_epochs``
    (reference: ``LearningRateWarmupCallback``, implementing the Goyal et
    al. linear-scaling + warmup recipe).

    ``momentum_correction`` is accepted for reference-API compatibility but
    has no effect here: it compensates for optimizer-internal momentum
    buffers when mutating a live torch/TF optimizer, whereas this callback
    sets ``state.lr`` consumed afresh by the train step.
    """

    def __init__(self, initial_lr: float, warmup_epochs: int = 5,
                 momentum_correction: bool = True, verbose: int = 0):
        self.warmup_epochs = warmup_epochs
        self.verbose = verbose

        def multiplier(epoch):
            size = basics.size() if basics.is_initialized() else 1
            # epoch+1 so the first epoch already makes progress; the last
            # warmup epoch lands exactly on size().
            return 1.0 + (size - 1.0) * (epoch + 1) / max(warmup_epochs, 1)

        # end_epoch bounds the warmup (reference behavior) so composed decay
        # schedules own the LR afterwards.
        super().__init__(initial_lr, multiplier, start_epoch=0,
                         end_epoch=warmup_epochs)

    def on_epoch_begin(self, epoch: int, state: Any = None):
        super().on_epoch_begin(epoch, state)
        if self.verbose and state is not None and self._in_range(epoch):
            print(f"Epoch {epoch}: warmup lr = {state.lr:.6g}")


def warmup_scaled_schedule(base_lr: float, steps_per_epoch: int,
                           warmup_epochs: int = 5,
                           size: Optional[int] = None):
    """The same policy as an optax schedule (step-indexed), the idiomatic
    in-graph form: linear warmup from ``base_lr`` to ``base_lr * size`` over
    ``warmup_epochs`` epochs, constant after."""
    import optax
    n = size if size is not None else (
        basics.size() if basics.is_initialized() else 1)
    warmup_steps = max(warmup_epochs * steps_per_epoch, 1)
    return optax.linear_schedule(base_lr, base_lr * n, warmup_steps)
