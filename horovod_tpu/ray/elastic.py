"""Elastic Ray executor (reference: ``horovod/ray/elastic_v2.py`` —
SURVEY.md §2b P12, VERDICT missing #7).

Bridges the elastic machinery (``horovod_tpu/elastic/driver.py``) to Ray
actor lifecycles:

- **Discovery** = the Ray cluster's live node set (:class:`RayHostDiscovery`
  polls ``ray.nodes()``), so autoscaler node add/remove becomes host
  add/remove exactly like the reference's discovery-script polling;
- **Workers** = Ray actors instead of ssh-spawned processes: the driver's
  spawn hook creates an actor pinned to the assigned node and wraps the
  (actor, running ObjectRef) pair in a Popen-shaped adapter, so the
  driver's liveness/blacklist/regeneration loop works unchanged — a killed
  actor reads as a failed process, the node is blacklisted, and the world
  re-forms at reduced size;
- Workers long-poll the driver's versioned rendezvous for assignments, the
  same protocol the process-based elastic path uses.

Ray is not installed in the TPU test image; the executor degrades to a
clear ImportError from :func:`_require_ray`, and every Ray API touch goes
through an injectable handle so the orchestration is testable with fakes
(the reference tests elastic_v2 the same way — mock clusters).
"""

from __future__ import annotations

import os
from typing import Any, Callable, Dict, List, Optional

from .runner import _require_ray
from ..elastic.discovery import DiscoveredHost, HostDiscovery
from ..elastic.driver import ElasticDriver
from ..utils.logging import get_logger

log = get_logger()


class RayHostDiscovery(HostDiscovery):
    """Live Ray nodes → discovered hosts (reference:
    ``elastic_v2.RayHostDiscovery``).

    Slots per node: the accelerator count when ``use_accelerators`` (TPU
    first, then GPU), else ``CPU // cpus_per_worker``.
    """

    def __init__(self, use_accelerators: bool = True,
                 cpus_per_worker: int = 1, ray_api=None):
        self.use_accelerators = use_accelerators
        self.cpus_per_worker = max(1, cpus_per_worker)
        self._ray = ray_api

    def find_available_hosts_and_slots(self) -> List[DiscoveredHost]:
        ray = self._ray or _require_ray()
        hosts: List[DiscoveredHost] = []
        for n in ray.nodes():
            if not n.get("Alive"):
                continue
            res = n.get("Resources", {})
            slots = 0
            if self.use_accelerators:
                slots = int(res.get("TPU", res.get("GPU", 0)))
            if slots == 0:
                slots = int(res.get("CPU", 0)) // self.cpus_per_worker
            if slots > 0:
                hosts.append(DiscoveredHost(n["NodeManagerAddress"], slots))
        return hosts


class _ActorProc:
    """Popen-shaped adapter over a (Ray actor, running ObjectRef) pair so
    the elastic driver's reap/terminate loop treats actors as workers."""

    def __init__(self, ray_api, actor, ref):
        self._ray = ray_api
        self._actor = actor
        self._ref = ref
        self.returncode: Optional[int] = None
        self.pid = f"actor:{id(actor):x}"

    def poll(self) -> Optional[int]:
        if self.returncode is not None:
            return self.returncode
        done, _ = self._ray.wait([self._ref], timeout=0)
        if not done:
            return None
        try:
            self.result = self._ray.get(done[0])
            self.returncode = 0
        except Exception as exc:  # noqa: BLE001 - actor death / user error
            log.warning("ray elastic: worker actor failed: %s", exc)
            self.returncode = 1
        return self.returncode

    def terminate(self):
        try:
            self._ray.kill(self._actor)
        except Exception:  # noqa: BLE001 - already dead
            pass
        if self.returncode is None:
            self.returncode = -15

    kill = terminate


class _RayElasticDriver(ElasticDriver):
    """ElasticDriver whose spawn creates Ray actors instead of processes.

    Actors run ``train_fn`` as a one-shot closure with env baked at spawn,
    so they cannot re-rank in place when the world changes the way the
    process path's rendezvous long-poll allows — every generation therefore
    kills and respawns the full actor set (``respawn_on_generation``) with
    the complete world assignment in the environment.
    """

    respawn_on_generation = True

    def __init__(self, *args, executor: "ElasticRayExecutor", **kwargs):
        super().__init__(*args, **kwargs)
        self._executor = executor

    def _spawn(self, identity: str, assignment: dict):
        env = self._worker_env(identity, assignment["hostname"],
                               assignment["local_rank"])
        # One-shot actors see their whole world statically (no rendezvous
        # long-poll), so the full assignment rides the environment.
        env.update({
            "HOROVOD_RANK": str(assignment["rank"]),
            "HOROVOD_SIZE": str(assignment["size"]),
            "HOROVOD_LOCAL_SIZE": str(assignment["local_size"]),
            "HOROVOD_CROSS_RANK": str(assignment["cross_rank"]),
            "HOROVOD_CROSS_SIZE": str(assignment["cross_size"]),
            "HOROVOD_CONTROLLER_ADDR": assignment["controller_addr"],
            "HOROVOD_CONTROLLER_PORT": str(assignment["controller_port"]),
            "HOROVOD_CONTROLLER_PORT2": str(
                assignment["controller_port2"]),
        })
        hvd_env = {k: v for k, v in env.items()
                   if k.startswith("HOROVOD_")}
        proc = self._executor._make_actor(assignment["hostname"], hvd_env)
        self._procs[identity] = proc
        self.registry.record_ready(identity)
        if self.verbose:
            log.warning("ray elastic: spawned %s (%s)", identity, proc.pid)


class ElasticRayExecutor:
    """Reference-compatible elastic executor facade::

        executor = ElasticRayExecutor(min_workers=2, max_workers=8)
        executor.start()
        rc = executor.run(train_fn)     # train_fn uses @hvd.elastic.run

    ``train_fn`` runs inside each worker actor with the elastic HOROVOD_*
    environment set; host changes flow through the standard rendezvous /
    notification path.
    """

    def __init__(self, min_workers: int = 1,
                 max_workers: Optional[int] = None,
                 use_accelerators: bool = True, cpus_per_worker: int = 1,
                 env_vars: Optional[Dict[str, str]] = None,
                 override_discovery: Optional[HostDiscovery] = None,
                 discovery_interval_s: float = 1.0,
                 start_timeout_s: float = 600.0, verbose: int = 0,
                 _ray_api=None):
        self.min_workers = min_workers
        self.max_workers = max_workers
        self.use_accelerators = use_accelerators
        self.cpus_per_worker = cpus_per_worker
        self.env_vars = dict(env_vars or {})
        self.discovery = override_discovery or RayHostDiscovery(
            use_accelerators, cpus_per_worker, ray_api=_ray_api)
        self.discovery_interval_s = discovery_interval_s
        self.start_timeout_s = start_timeout_s
        self.verbose = verbose
        self._ray = _ray_api
        self._train_fn: Optional[Callable] = None
        self.driver: Optional[_RayElasticDriver] = None

    def start(self):
        """Validate Ray is importable/initialized (actors spawn lazily per
        elastic generation inside :meth:`run`)."""
        ray = self._ray or _require_ray()
        if hasattr(ray, "is_initialized") and not ray.is_initialized():
            ray.init(address="auto")

    # ------------------------------------------------------------- actors
    def _make_actor(self, hostname: str, env: Dict[str, str]) -> _ActorProc:
        ray = self._ray or _require_ray()
        full_env = {**self.env_vars, **env}

        @ray.remote(num_cpus=self.cpus_per_worker,
                    max_restarts=0)
        class _ElasticWorker:
            def execute(self, env, fn):
                os.environ.update(env)
                return fn()

        # Soft node pinning via Ray's per-node resource: the assignment's
        # env (HOSTNAME/LOCAL_RANK) is only valid on that node.
        actor = _ElasticWorker.options(
            resources={f"node:{hostname}": 0.001}).remote()
        ref = actor.execute.remote(full_env, self._train_fn)
        return _ActorProc(ray, actor, ref)

    # ---------------------------------------------------------------- run
    def run(self, train_fn: Callable[[], Any]) -> int:
        """Run ``train_fn`` elastically; returns the driver's exit code
        (0 = some rank finished training successfully)."""
        self._train_fn = train_fn
        self.driver = _RayElasticDriver(
            discovery=self.discovery, command=[],
            min_np=self.min_workers, max_np=self.max_workers,
            env=self.env_vars,
            discovery_interval_s=self.discovery_interval_s,
            start_timeout_s=self.start_timeout_s,
            verbose=self.verbose, executor=self)
        try:
            return self.driver.run()
        finally:
            self.driver.rendezvous.stop()

    def shutdown(self):
        if self.driver is not None:
            self.driver._shutdown_workers()
