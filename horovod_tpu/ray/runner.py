"""``RayExecutor``: run horovod_tpu jobs as Ray actors.

Parity: reference ``horovod/ray/runner.py`` (SURVEY.md §2b P12) —
``RayExecutor(settings, num_workers=..., use_gpu=...)`` with
``start() / run(fn) / run_remote(fn) / execute(fn) / shutdown()``.

Placement (pack/spread over the cluster's node inventory) is computed by
the pure strategies in ``strategy.py``; this module only does the thin Ray
actor orchestration, and degrades to a clear ImportError when Ray is not
installed (Ray is not part of the TPU image — the API surface is kept so
Ray-based codebases can port unchanged).
"""

from __future__ import annotations

import os
from typing import Any, Callable, Dict, List, Optional

from . import strategy as _strategy


def _require_ray():
    try:
        import ray  # noqa: F401
        return ray
    except ImportError as exc:  # pragma: no cover - ray not in image
        raise ImportError(
            "horovod_tpu.ray requires the `ray` package, which is not "
            "installed in this environment. The placement strategies "
            "(horovod_tpu.ray.strategy) work standalone; install ray to "
            "launch actors.") from exc


class RayExecutor:
    """Reference-compatible executor facade.

    Example (with ray installed)::

        executor = RayExecutor(num_workers=8, use_accelerators=True)
        executor.start()
        results = executor.run(train_fn, args=(cfg,))
        executor.shutdown()
    """

    def __init__(self, settings: Optional[dict] = None,
                 num_workers: int = 1, cpus_per_worker: int = 1,
                 use_accelerators: bool = True,
                 placement: str = "pack", env_vars: Optional[Dict] = None):
        if placement not in ("pack", "spread"):
            raise ValueError("placement must be 'pack' or 'spread'")
        self.settings = settings or {}
        self.num_workers = num_workers
        self.cpus_per_worker = cpus_per_worker
        self.use_accelerators = use_accelerators
        self.placement = placement
        self.env_vars = dict(env_vars or {})
        self.workers: List[Any] = []
        self._allocations: List[_strategy.Allocation] = []

    # ------------------------------------------------------------ placement
    def compute_placement(self, nodes) -> List[_strategy.Allocation]:
        fn = _strategy.pack if self.placement == "pack" else _strategy.spread
        self._allocations = fn(nodes, self.num_workers,
                               self.use_accelerators)
        return self._allocations

    def worker_env(self, alloc: _strategy.Allocation,
                   coordinator: tuple) -> Dict[str, str]:
        """The HOROVOD_* env one worker actor exports before hvd.init()."""
        hosts = []
        for a in self._allocations:
            if a.hostname not in hosts:
                hosts.append(a.hostname)
        local_size = sum(1 for a in self._allocations
                         if a.hostname == alloc.hostname)
        env = {
            "HOROVOD_RANK": str(alloc.rank),
            "HOROVOD_SIZE": str(len(self._allocations)),
            "HOROVOD_LOCAL_RANK": str(alloc.local_rank),
            "HOROVOD_LOCAL_SIZE": str(local_size),
            "HOROVOD_CROSS_RANK": str(alloc.cross_rank),
            "HOROVOD_CROSS_SIZE": str(len(hosts)),
            "HOROVOD_CONTROLLER_ADDR": coordinator[0],
            "HOROVOD_CONTROLLER_PORT": str(coordinator[1]),
            "HOROVOD_CONTROLLER_PORT2": str(coordinator[2]),
            "HOROVOD_HOSTNAME": alloc.hostname,
        }
        env.update({k: str(v) for k, v in self.env_vars.items()})
        return env

    # ------------------------------------------------------------ lifecycle
    def start(self):
        ray = _require_ray()
        from ray.util.scheduling_strategies import (
            NodeAffinitySchedulingStrategy)
        from ..common.net import free_ports, is_local_host, remote_ports

        live = [n for n in ray.nodes() if n.get("Alive")]
        nodes = [
            _strategy.NodeResources(
                hostname=n["NodeManagerAddress"],
                cpus=int(n["Resources"].get("CPU", 0)),
                accelerators=int(n["Resources"].get(
                    "TPU", n["Resources"].get("GPU", 0))))
            for n in live]
        node_ids = {n["NodeManagerAddress"]: n["NodeID"] for n in live}
        allocations = self.compute_placement(nodes)
        # Ports must be free on the COORDINATOR node, not the driver; when
        # it is a different machine bind-probing here proves nothing.
        coord_host = allocations[0].hostname
        ports = (free_ports(2) if is_local_host(coord_host)
                 else remote_ports(2, os.getpid()))
        coord = (coord_host, *ports)

        @ray.remote(num_cpus=self.cpus_per_worker)
        class _Worker:
            def __init__(self, env):
                os.environ.update(env)

            def execute(self, fn, *args, **kwargs):
                return fn(*args, **kwargs)

        # Pin each actor to the node its assignment names — the env
        # (HOSTNAME/LOCAL_RANK/controller address) is only valid there.
        self.workers = [
            _Worker.options(
                scheduling_strategy=NodeAffinitySchedulingStrategy(
                    node_id=node_ids[a.hostname], soft=False),
            ).remote(self.worker_env(a, coord))
            for a in allocations]

    def run(self, fn: Callable, args=(), kwargs=None) -> List[Any]:
        """Run ``fn`` on every worker; block for all results."""
        ray = _require_ray()
        return ray.get(self.run_remote(fn, args, kwargs))

    def run_remote(self, fn: Callable, args=(), kwargs=None) -> List[Any]:
        _require_ray()
        kwargs = kwargs or {}
        return [w.execute.remote(fn, *args, **kwargs) for w in self.workers]

    def execute(self, fn: Callable) -> List[Any]:
        """Apply ``fn(worker)`` on each actor (reference API)."""
        ray = _require_ray()
        return ray.get([w.execute.remote(fn) for w in self.workers])

    def shutdown(self):
        if not self.workers:
            return
        ray = _require_ray()
        for w in self.workers:
            ray.kill(w)
        self.workers = []
