"""Ray integration (reference: ``horovod/ray`` — SURVEY.md §2b P12).

``RayExecutor`` places workers as Ray actors; ``strategy`` holds the pure
pack/spread placement logic (usable and tested without Ray installed).
"""

from .runner import RayExecutor  # noqa: F401
from .strategy import Allocation, NodeResources, pack, spread  # noqa: F401
from .elastic import ElasticRayExecutor, RayHostDiscovery  # noqa: F401
