"""Worker-placement strategies for the Ray executor.

Parity: reference ``horovod/ray/strategy.py`` — pack vs. spread colocation
of workers onto cluster nodes.  Pure functions of the node inventory so the
logic is testable without a Ray cluster (the reference tests the same way,
SURVEY.md §4 ``test_ray.py``).

TPU note: a "node" here is a TPU VM worker; ``accelerators_per_node`` maps
to chips per VM, and pack-by-slice keeps workers on the same ICI domain.
"""

from __future__ import annotations

import dataclasses
from typing import Dict, List, Tuple


@dataclasses.dataclass(frozen=True)
class NodeResources:
    hostname: str
    cpus: int = 0
    accelerators: int = 0


@dataclasses.dataclass(frozen=True)
class Allocation:
    """One worker's placement."""
    hostname: str
    local_rank: int
    rank: int
    cross_rank: int


def _allocate(counts: List[Tuple[str, int]]) -> List[Allocation]:
    out: List[Allocation] = []
    rank = 0
    for cross_rank, (host, n) in enumerate(counts):
        for local_rank in range(n):
            out.append(Allocation(host, local_rank, rank, cross_rank))
            rank += 1
    return out


def pack(nodes: List[NodeResources], num_workers: int,
         use_accelerators: bool = True) -> List[Allocation]:
    """Fill each node to capacity before moving on (minimizes hosts used →
    maximizes intra-host/ICI communication).  Reference: PackStrategy."""
    counts: List[Tuple[str, int]] = []
    remaining = num_workers
    for node in nodes:
        cap = node.accelerators if use_accelerators else node.cpus
        take = min(cap, remaining)
        if take > 0:
            counts.append((node.hostname, take))
            remaining -= take
        if remaining == 0:
            break
    if remaining > 0:
        total = sum(n.accelerators if use_accelerators else n.cpus
                    for n in nodes)
        raise ValueError(
            f"Cannot place {num_workers} workers: cluster capacity {total}")
    return _allocate(counts)


def spread(nodes: List[NodeResources], num_workers: int,
           use_accelerators: bool = True) -> List[Allocation]:
    """Round-robin workers across as many nodes as possible (maximizes
    aggregate host NIC/DCN bandwidth).  Reference: SpreadStrategy."""
    caps = {n.hostname: (n.accelerators if use_accelerators else n.cpus)
            for n in nodes}
    counts: Dict[str, int] = {n.hostname: 0 for n in nodes}
    placed = 0
    while placed < num_workers:
        progressed = False
        for n in nodes:
            if placed == num_workers:
                break
            if counts[n.hostname] < caps[n.hostname]:
                counts[n.hostname] += 1
                placed += 1
                progressed = True
        if not progressed:
            raise ValueError(
                f"Cannot place {num_workers} workers: cluster capacity "
                f"{sum(caps.values())}")
    ordered = [(n.hostname, counts[n.hostname]) for n in nodes
               if counts[n.hostname] > 0]
    return _allocate(ordered)
