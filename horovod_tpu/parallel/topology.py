"""Slice-level topology for two-level (ICI/DCN) collectives.

The data-plane twin of the hierarchical *control* plane (ISSUE 8): where the
controller tree groups ranks by host, the fused data plane groups ranks by
**slice** — the unit whose interior links are ICI and whose exterior links
are DCN.  This module derives that structure once, from device attributes,
and hands the engine everything it needs to lay a (cross, local) mesh over
the already-ordered rank list:

- **slice membership** — which contiguous block of ranks shares ICI.  On
  real multi-slice TPU worlds every ``jax.Device`` carries a
  ``slice_index`` attribute; CPU/simulated worlds use the explicit
  ``HOROVOD_SLICE_MAP`` override (see :func:`parse_slice_map`), the
  ``HOROVOD_HIERARCHICAL_LOCAL_SIZE`` knob, or the per-process device
  counts, in that precedence order (:func:`slice_topology`).
- **torus coordinates** — per-rank physical coords when the platform
  exposes them; the cross-slice ring order is derived from the *leaders'*
  coordinates so the DCN ring visits slices in physical-neighbor order
  instead of slice-id order.
- **a per-slice leader set** — rank 0 of each slice, the natural process
  set for cross-slice work (the engine's cross mesh axis, leader-only
  broadcasts, tests).

Everything here is pure Python over duck-typed device objects — **no jax
import** — so the purity tier can load it with jax hard-blocked and the
analyzer/bench can model wire bytes without touching a backend.

The whole module leans on one invariant established by
``common.topology.ordered_devices``: ranks are assigned slice-major (slice
index first, torus coords within), so slice membership is always a
partition into *contiguous, equal* rank blocks — exactly what a
``reshape(num_slices, local_size)`` of the world device list needs.
"""

from __future__ import annotations

import dataclasses
from typing import Dict, List, Optional, Sequence, Tuple


@dataclasses.dataclass(frozen=True)
class SliceTopology:
    """Two-level structure of an ordered rank world.

    ``slice_of[r]`` is the 0-based slice of rank ``r``; blocks are
    contiguous and uniform (``local_size`` ranks each).  ``leaders`` holds
    the first rank of every slice, indexed by slice id.  ``cross_order``
    lists slice ids in DCN ring order — leader torus coordinates
    lexicographically when known, slice-id order otherwise."""

    world: int
    num_slices: int
    local_size: int
    slice_of: Tuple[int, ...]
    leaders: Tuple[int, ...]
    cross_order: Tuple[int, ...]
    coords: Optional[Tuple[Optional[Tuple[int, ...]], ...]] = None

    def ranks_of_slice(self, s: int) -> List[int]:
        return [r for r in range(self.world) if self.slice_of[r] == s]

    def leader_set_ranks(self) -> List[int]:
        """Ranks of the per-slice leader process set, in cross ring order.

        Callers register it with ``hvd.add_process_set`` themselves (this
        module stays jax-free); the engine's cross mesh axis follows the
        same rank blocks, so leader-set collectives and the fused
        cross-slice leg see the same DCN ring."""
        return [self.leaders[s] for s in self.cross_order]


def parse_slice_map(text: str, world: int) -> Optional[Tuple[int, ...]]:
    """Parse ``HOROVOD_SLICE_MAP`` into a rank→slice tuple.

    Two spellings, both rank-order (the only order the engine's
    slice-major reshape supports):

    - ``"4"`` — uniform slice size: every consecutive block of 4 ranks is
      one slice.
    - ``"4,4"`` — explicit per-slice sizes (must sum to ``world``; sizes
      must be uniform, since the (cross, local) mesh is rectangular).

    Empty/None disables the override.  Malformed values raise
    ``ValueError`` — a mis-typed slice map silently falling back to flat
    would be invisible until the first multi-slice profile."""
    if not text:
        return None
    parts = [p.strip() for p in str(text).split(",") if p.strip()]
    try:
        sizes = [int(p) for p in parts]
    except ValueError:
        raise ValueError(f"HOROVOD_SLICE_MAP: non-integer entry in {text!r}")
    if len(sizes) == 1:
        local = sizes[0]
        if local <= 0 or world % local:
            raise ValueError(
                f"HOROVOD_SLICE_MAP={text!r}: slice size {local} does not "
                f"divide world {world}")
        sizes = [local] * (world // local)
    if sum(sizes) != world:
        raise ValueError(
            f"HOROVOD_SLICE_MAP={text!r}: sizes sum to {sum(sizes)}, "
            f"world is {world}")
    if any(s != sizes[0] for s in sizes):
        raise ValueError(
            f"HOROVOD_SLICE_MAP={text!r}: slice sizes must be uniform "
            f"(the hierarchical mesh is rectangular), got {sizes}")
    out: List[int] = []
    for s, n in enumerate(sizes):
        out.extend([s] * n)
    return tuple(out)


def _normalize(raw_ids: Sequence) -> Optional[Tuple[int, ...]]:
    """Map arbitrary slice labels to 0-based ids by first appearance,
    validating the contiguous-equal-blocks invariant."""
    ids: Dict = {}
    out: List[int] = []
    for v in raw_ids:
        if v not in ids:
            ids[v] = len(ids)
        out.append(ids[v])
    num = len(ids)
    if num <= 1:
        return None
    world = len(out)
    if world % num:
        return None
    local = world // num
    for r, s in enumerate(out):
        if s != r // local:
            return None            # non-contiguous or non-uniform blocks
    return tuple(out)


def slice_topology(devices: Optional[Sequence] = None, *,
                   world: Optional[int] = None,
                   slice_map: Optional[str] = None,
                   local_size: int = 0,
                   local_counts: Optional[Sequence[int]] = None,
                   ) -> Optional[SliceTopology]:
    """Derive the two-level structure, or None when the world is flat.

    Precedence (first that yields ≥2 slices of ≥2 ranks wins):

    1. ``slice_map`` — the explicit ``HOROVOD_SLICE_MAP`` override
       (CPU/simulated worlds; malformed values raise).
    2. ``slice_index`` device attributes — real multi-slice TPU worlds.
    3. ``local_size`` — the ``HOROVOD_HIERARCHICAL_LOCAL_SIZE`` knob.
    4. ``local_counts`` — one slice per process when every process holds
       the same device count (the PR-3 era host-based derivation).

    ``devices`` are duck-typed (only ``slice_index``/``coords`` are read,
    both optional) so tests can pass plain namespaces and the module
    never needs a backend."""
    if world is None:
        world = len(devices) if devices is not None else 0
    if world <= 3:                # 2 slices of 2 is the smallest two-level
        return None
    slice_of: Optional[Tuple[int, ...]] = None
    if slice_map:
        slice_of = parse_slice_map(slice_map, world)
    if slice_of is None and devices is not None:
        ids = [getattr(d, "slice_index", None) for d in devices]
        if all(i is not None for i in ids):
            slice_of = _normalize(ids)
    if slice_of is None and local_size > 1 \
            and world % local_size == 0 and world // local_size > 1:
        slice_of = tuple(r // local_size for r in range(world))
    if slice_of is None and local_counts:
        counts = list(local_counts)
        if len(counts) > 1 and counts[0] > 1 \
                and all(c == counts[0] for c in counts) \
                and sum(counts) == world:
            slice_of = tuple(r // counts[0] for r in range(world))
    if slice_of is None:
        return None
    num = slice_of[-1] + 1
    local = world // num
    if local <= 1 or num <= 1:
        return None
    leaders = tuple(s * local for s in range(num))
    coords: Optional[Tuple] = None
    if devices is not None:
        cs = tuple(tuple(c) if c is not None else None
                   for c in (getattr(d, "coords", None) for d in devices))
        if any(c is not None for c in cs):
            coords = cs
    cross_order = _cross_ring_order(leaders, coords)
    return SliceTopology(world=world, num_slices=num, local_size=local,
                         slice_of=slice_of, leaders=leaders,
                         cross_order=cross_order, coords=coords)


def _cross_ring_order(leaders: Tuple[int, ...],
                      coords: Optional[Tuple]) -> Tuple[int, ...]:
    """DCN ring order over slices: leaders sorted by torus coordinates
    (lexicographic — neighbors in the outermost DCN dimension end up
    adjacent in the ring), slice-id order when coords are unknown."""
    n = len(leaders)
    if coords is None:
        return tuple(range(n))
    def key(s: int):
        c = coords[leaders[s]] if leaders[s] < len(coords) else None
        return (0, c, s) if c is not None else (1, (), s)
    return tuple(sorted(range(n), key=key))


def hier_bit_orders(local_size: int, num_slices: int
                    ) -> Optional[Tuple[List[int], List[int]]]:
    """Per-level VHD round schedules ``(local_bits, cross_bits)``.

    Adasum's vector-halving-doubling needs power-of-two extents at each
    level; rounds walk bits low-to-high so the innermost (fastest ICI)
    dimension exchanges first — the fully-halved 1/local shard is what
    crosses DCN.  None when either extent is not a power of two (the
    engine's crossover decision then keeps the flat path)."""
    if local_size < 2 or num_slices < 2:
        return None
    if local_size & (local_size - 1) or num_slices & (num_slices - 1):
        return None
    return (list(range(local_size.bit_length() - 1)),
            list(range(num_slices.bit_length() - 1)))


def modeled_leg_bytes(nbytes: int, world: int, local_size: int
                      ) -> Dict[str, float]:
    """Ring-modeled per-rank wire bytes for a payload of ``nbytes``.

    ``flat``: one world ring allreduce — ``2·n·(W−1)/W``.
    ``intra``: the two ICI legs (reduce-scatter + allgather over the
    slice) — ``2·n·(L−1)/L``.  ``cross``: the DCN leg, an allreduce of
    the 1/L shard over the leader ring — ``2·(n/L)·(C−1)/C``, i.e. the
    slow links carry ≤ 1/local_size of the flat ring's bytes — the
    whole point of the two-level schedule."""
    world = max(1, int(world))
    local = max(1, int(local_size))
    cross = max(1, world // local)
    return {
        "flat": 2.0 * nbytes * (world - 1) / world,
        "intra": 2.0 * nbytes * (local - 1) / local,
        "cross": 2.0 * (nbytes / local) * (cross - 1) / cross,
    }


def cross_fraction(nbytes: int, world: int, local_size: int) -> float:
    """Modeled share of a hierarchical reduce's wire time on the cross
    (DCN) leg — the trace layer splits the ``reduce`` phase with this
    (hosts cannot stamp inside one XLA launch)."""
    legs = modeled_leg_bytes(max(1, nbytes), world, local_size)
    total = legs["intra"] + legs["cross"]
    return legs["cross"] / total if total > 0 else 0.0
