"""Ulysses-style sequence parallelism: alltoall head/sequence exchange.

No reference analogue (SURVEY.md §5: sequence parallelism absent), but built
directly on the primitive the reference *does* ship — ``hvd.alltoall``
(reference ``horovod/common/ops/*Alltoall``, the DLRM exchange primitive) —
here as ``lax.all_to_all`` over the ``sp`` mesh axis.

Scheme (DeepSpeed-Ulysses): attention is local in the head dimension, so
convert a sequence-sharded layout ``[B, T/sp, H, D]`` into a head-sharded
layout ``[B, T, H/sp, D]`` with one alltoall, run full-sequence attention on
the local heads, and alltoall back.  Two alltoalls per attention vs ring's
(sp-1) permutes; wins when heads >= sp and ICI alltoall bandwidth is good.
"""

from __future__ import annotations

from typing import Callable, Optional

from jax import lax
from ..compat import axis_size as compat_axis_size


def seq_to_heads(x, axis_name: str = "sp"):
    """[B, T/sp, H, D] -> [B, T, H/sp, D] via alltoall over sp."""
    return lax.all_to_all(x, axis_name, split_axis=2, concat_axis=1,
                          tiled=True)


def heads_to_seq(x, axis_name: str = "sp"):
    """[B, T, H/sp, D] -> [B, T/sp, H, D] — inverse alltoall."""
    return lax.all_to_all(x, axis_name, split_axis=1, concat_axis=2,
                          tiled=True)


def ulysses_attention(q, k, v, attn_fn: Optional[Callable] = None,
                      axis_name: str = "sp", causal: bool = False):
    """Attention over an sp-sharded sequence via head exchange.

    ``attn_fn(q, k, v, causal=...)`` runs on full-sequence, local-head
    tensors; defaults to the exact flash reference implementation.
    Requires heads divisible by sp.
    """
    if attn_fn is None:
        from ..ops.flash_attention import flash_attention, flash_enabled
        # The inner attention sees the FULL gathered sequence (T_local·sp).
        if flash_enabled(seq=q.shape[1] * compat_axis_size(axis_name),
                         causal=causal):
            attn_fn = flash_attention   # pallas kernel on the local heads
        else:
            from .ring_attention import local_flash_attention
            attn_fn = local_flash_attention
    H = q.shape[2]
    K = k.shape[2]
    n = compat_axis_size(axis_name)
    if H % n or K % n:
        raise ValueError(
            f"ulysses_attention needs q heads ({H}) AND kv heads ({K}) "
            f"divisible by the {axis_name!r} axis size ({n}) — GQA kv "
            f"travels un-repeated through the alltoall; use "
            f"ring_attention when the kv head count is below the sp "
            f"degree")
    qh = seq_to_heads(q, axis_name)
    kh = seq_to_heads(k, axis_name)
    vh = seq_to_heads(v, axis_name)
    out = attn_fn(qh, kh, vh, causal=causal)
    return heads_to_seq(out, axis_name)
