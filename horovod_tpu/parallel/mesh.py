"""Multi-axis mesh construction for dp/tp/sp/ep parallelism.

The reference is data-parallel only (SURVEY.md §2c); its process sets +
alltoall/allgather primitives are the enabling layer for everything else.
Here the enabling layer is mesh-native: a ``jax.sharding.Mesh`` with named
axes, ICI-topology-ordered (``common/topology.py``), over which
``ops/collectives.py`` primitives and the ``parallel/`` schemes compose.

Axis conventions used across the framework:

- ``dp``: data parallel (gradient allreduce — the Horovod axis)
- ``tp``: tensor parallel (Megatron-style sharded matmuls)
- ``sp``: sequence/context parallel (ring attention / Ulysses)
- ``ep``: expert parallel (MoE / DLRM embedding alltoall)
- ``pp``: pipeline stages (microbatched lax.scan pipeline)
"""

from __future__ import annotations

from typing import Dict, Optional, Sequence, Tuple

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec

from ..common.topology import ordered_devices

DP, TP, SP, EP, PP = "dp", "tp", "sp", "ep", "pp"


# ---------------------------------------------------------------------------
# hvd process-set <-> jax.sharding mesh interop (ISSUE 15)
# ---------------------------------------------------------------------------

def process_set_mesh(process_set=None,
                     axis_name: Optional[str] = None) -> Mesh:
    """The ``jax.sharding.Mesh`` spanned by an hvd process set.

    The translation layer that lets ``shard_map``-partitioned step
    functions compose with the eager engine: the SAME devices, in the
    SAME (negotiated) rank order, under a caller-chosen axis name — so a
    ``lax.psum`` over this mesh reduces over exactly the ranks an eager
    ``hvd.allreduce(process_set=...)`` would, and a sharded optimizer's
    1/N shard layout matches the engine's reduce-scatter slices.

    ``process_set=None`` is the global world.  ``axis_name=None`` keeps
    the set's own axis name (``"hvd"`` for the world); passing e.g.
    ``"dp"`` relabels the axis for reuse with the ``parallel`` helpers
    (same devices, same order — only the label changes).
    """
    from ..common import basics
    st = basics._get_state()
    ps_id = 0 if process_set is None or process_set.process_set_id is None \
        else process_set.process_set_id
    ps = st.process_set_table.get(ps_id)
    m = ps.mesh
    if axis_name is None or (axis_name,) == tuple(m.axis_names):
        return m
    return Mesh(np.asarray(m.devices), (axis_name,))


def process_set_spec(process_set=None,
                     axis_name: Optional[str] = None) -> PartitionSpec:
    """``PartitionSpec`` sharding dim 0 over the process set's axis — the
    spec of a stacked per-rank ``[world, *S]`` engine tensor on
    :func:`process_set_mesh`."""
    if axis_name is not None:
        return PartitionSpec(axis_name)
    from ..common import basics
    st = basics._get_state()
    ps_id = 0 if process_set is None or process_set.process_set_id is None \
        else process_set.process_set_id
    return PartitionSpec(st.process_set_table.get(ps_id).axis_name)


def process_set_sharding(process_set=None,
                         axis_name: Optional[str] = None) -> NamedSharding:
    """``NamedSharding`` for stacked per-rank tensors of a process set —
    hand this to ``jax.device_put``/``jax.jit`` in/out shardings so
    arrays flow between a partitioned step function and the eager engine
    without resharding copies."""
    return NamedSharding(process_set_mesh(process_set, axis_name),
                         process_set_spec(process_set, axis_name))


def axes_of(mesh: Mesh) -> Tuple[str, ...]:
    """The mesh's named axes, in physical order.  The runtime twin of the
    analyzer's mesh-axis extraction (HVD112): code that builds collective
    axis names dynamically should validate them against this set."""
    return tuple(str(a) for a in mesh.axis_names)


def require_axis(mesh: Mesh, axis_name: str) -> str:
    """Assert ``axis_name`` is bound by ``mesh`` and return it.

    The runtime counterpart of HVD112: a collective over an axis its
    binding mesh does not define either fails deep inside lowering with
    an unhelpful traceback or — worse, with an outer binding in scope —
    silently reduces over the WRONG axis.  Call this where the axis name
    is computed rather than literal (literal names are already covered
    statically by ``collective_lint``/``trace_check``)."""
    names = axes_of(mesh)
    if axis_name not in names:
        raise ValueError(
            f"axis {axis_name!r} is not bound by this mesh (axes: "
            f"{list(names)}) — a collective over it would fail at "
            f"lowering or reduce over the wrong communicator (HVD112)")
    return axis_name


def make_mesh(axis_sizes: Dict[str, int],
              devices: Optional[Sequence[jax.Device]] = None) -> Mesh:
    """Build a named mesh, e.g. ``make_mesh({"dp": 2, "tp": 2, "sp": 2})``.

    Axis order in the dict is the physical order: the **last** axis varies
    fastest over ICI-neighbor devices, so put the most communication-hungry
    axis (usually ``tp``) last — the standard TPU layout rule (ICI-neighbor
    collectives are cheapest).
    """
    devs = ordered_devices(devices)
    sizes = list(axis_sizes.values())
    total = int(np.prod(sizes))
    if total != len(devs):
        raise ValueError(
            f"Mesh axes {axis_sizes} require {total} devices, have {len(devs)}")
    arr = np.array(devs, dtype=object).reshape(sizes)
    return Mesh(arr, tuple(axis_sizes.keys()))


def infer_mesh(n_devices: int,
               tp: int = 1, sp: int = 1, ep: int = 1, pp: int = 1,
               devices: Optional[Sequence[jax.Device]] = None) -> Mesh:
    """dp fills whatever the fixed axes leave over."""
    denom = tp * sp * ep * pp
    if n_devices % denom:
        raise ValueError(f"{n_devices} devices not divisible by tp*sp*ep*pp={denom}")
    # All axes always present (size-1 axes are free) so PartitionSpecs can
    # reference any of them unconditionally.
    axes = {DP: n_devices // denom, PP: pp, EP: ep, SP: sp, TP: tp}
    return make_mesh(axes, devices)
