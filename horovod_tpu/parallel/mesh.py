"""Multi-axis mesh construction for dp/tp/sp/ep parallelism.

The reference is data-parallel only (SURVEY.md §2c); its process sets +
alltoall/allgather primitives are the enabling layer for everything else.
Here the enabling layer is mesh-native: a ``jax.sharding.Mesh`` with named
axes, ICI-topology-ordered (``common/topology.py``), over which
``ops/collectives.py`` primitives and the ``parallel/`` schemes compose.

Axis conventions used across the framework:

- ``dp``: data parallel (gradient allreduce — the Horovod axis)
- ``tp``: tensor parallel (Megatron-style sharded matmuls)
- ``sp``: sequence/context parallel (ring attention / Ulysses)
- ``ep``: expert parallel (MoE / DLRM embedding alltoall)
- ``pp``: pipeline stages (microbatched lax.scan pipeline)
"""

from __future__ import annotations

from typing import Dict, Optional, Sequence, Tuple

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec

from ..common.topology import ordered_devices

DP, TP, SP, EP, PP = "dp", "tp", "sp", "ep", "pp"


# ---------------------------------------------------------------------------
# hvd process-set <-> jax.sharding mesh interop (ISSUE 15)
# ---------------------------------------------------------------------------

def process_set_mesh(process_set=None,
                     axis_name: Optional[str] = None) -> Mesh:
    """The ``jax.sharding.Mesh`` spanned by an hvd process set.

    The translation layer that lets ``shard_map``-partitioned step
    functions compose with the eager engine: the SAME devices, in the
    SAME (negotiated) rank order, under a caller-chosen axis name — so a
    ``lax.psum`` over this mesh reduces over exactly the ranks an eager
    ``hvd.allreduce(process_set=...)`` would, and a sharded optimizer's
    1/N shard layout matches the engine's reduce-scatter slices.

    ``process_set=None`` is the global world.  ``axis_name=None`` keeps
    the set's own axis name (``"hvd"`` for the world); passing e.g.
    ``"dp"`` relabels the axis for reuse with the ``parallel`` helpers
    (same devices, same order — only the label changes).
    """
    from ..common import basics
    st = basics._get_state()
    ps_id = 0 if process_set is None or process_set.process_set_id is None \
        else process_set.process_set_id
    ps = st.process_set_table.get(ps_id)
    m = ps.mesh
    if axis_name is None or (axis_name,) == tuple(m.axis_names):
        return m
    return Mesh(np.asarray(m.devices), (axis_name,))


def process_set_spec(process_set=None,
                     axis_name: Optional[str] = None) -> PartitionSpec:
    """``PartitionSpec`` sharding dim 0 over the process set's axis — the
    spec of a stacked per-rank ``[world, *S]`` engine tensor on
    :func:`process_set_mesh`."""
    if axis_name is not None:
        return PartitionSpec(axis_name)
    from ..common import basics
    st = basics._get_state()
    ps_id = 0 if process_set is None or process_set.process_set_id is None \
        else process_set.process_set_id
    return PartitionSpec(st.process_set_table.get(ps_id).axis_name)


def process_set_sharding(process_set=None,
                         axis_name: Optional[str] = None) -> NamedSharding:
    """``NamedSharding`` for stacked per-rank tensors of a process set —
    hand this to ``jax.device_put``/``jax.jit`` in/out shardings so
    arrays flow between a partitioned step function and the eager engine
    without resharding copies."""
    return NamedSharding(process_set_mesh(process_set, axis_name),
                         process_set_spec(process_set, axis_name))


def axes_of(mesh: Mesh) -> Tuple[str, ...]:
    """The mesh's named axes, in physical order.  The runtime twin of the
    analyzer's mesh-axis extraction (HVD112): code that builds collective
    axis names dynamically should validate them against this set."""
    return tuple(str(a) for a in mesh.axis_names)


def require_axis(mesh: Mesh, axis_name: str) -> str:
    """Assert ``axis_name`` is bound by ``mesh`` and return it.

    The runtime counterpart of HVD112: a collective over an axis its
    binding mesh does not define either fails deep inside lowering with
    an unhelpful traceback or — worse, with an outer binding in scope —
    silently reduces over the WRONG axis.  Call this where the axis name
    is computed rather than literal (literal names are already covered
    statically by ``collective_lint``/``trace_check``)."""
    names = axes_of(mesh)
    if axis_name not in names:
        raise ValueError(
            f"axis {axis_name!r} is not bound by this mesh (axes: "
            f"{list(names)}) — a collective over it would fail at "
            f"lowering or reduce over the wrong communicator (HVD112)")
    return axis_name


def make_mesh(axis_sizes: Dict[str, int],
              devices: Optional[Sequence[jax.Device]] = None) -> Mesh:
    """Build a named mesh, e.g. ``make_mesh({"dp": 2, "tp": 2, "sp": 2})``.

    Axis order in the dict is the physical order: the **last** axis varies
    fastest over ICI-neighbor devices, so put the most communication-hungry
    axis (usually ``tp``) last — the standard TPU layout rule (ICI-neighbor
    collectives are cheapest).
    """
    devs = ordered_devices(devices)
    sizes = list(axis_sizes.values())
    total = int(np.prod(sizes))
    if total != len(devs):
        raise ValueError(
            f"Mesh axes {axis_sizes} require {total} devices, have {len(devs)}")
    arr = np.array(devs, dtype=object).reshape(sizes)
    return Mesh(arr, tuple(axis_sizes.keys()))


def infer_mesh(n_devices: int,
               tp: int = 1, sp: int = 1, ep: int = 1, pp: int = 1,
               devices: Optional[Sequence[jax.Device]] = None) -> Mesh:
    """dp fills whatever the fixed axes leave over."""
    denom = tp * sp * ep * pp
    if n_devices % denom:
        raise ValueError(f"{n_devices} devices not divisible by tp*sp*ep*pp={denom}")
    # All axes always present (size-1 axes are free) so PartitionSpecs can
    # reference any of them unconditionally.
    axes = {DP: n_devices // denom, PP: pp, EP: ep, SP: sp, TP: tp}
    return make_mesh(axes, devices)


# ---------------------------------------------------------------------------
# FSDP axis layout (ISSUE 18) — canonical PartitionSpecs per parameter
# family for data/fsdp/tp meshes, following the SpecLayout exemplar in
# SNIPPETS [2]: one frozen value object names the mesh axes once, and every
# spec the training step needs derives from it, so renaming an axis (or
# collapsing fsdp into dp on a pure-FSDP fleet) is a one-line change.
# ---------------------------------------------------------------------------

import dataclasses


@dataclasses.dataclass(frozen=True)
class SpecLayout:
    """Canonical partition specs for a (data, fsdp, tp) mesh.

    ``data_axis`` batches, ``fsdp_axis`` shards parameters ZeRO-3-style
    (``parallel/zero.py``'s pad+slice convention rides it), ``tp_axis``
    shards matmuls Megatron-style.  A pure-FSDP world sets
    ``data_axis == fsdp_axis`` — the specs still compose because every
    method references axes by field, never by literal."""
    data_axis: str = DP
    fsdp_axis: str = "fsdp"
    tp_axis: str = TP

    # ---- activations -----------------------------------------------------
    def batch(self) -> PartitionSpec:
        """Per-example activations: batch dim over data (and fsdp, when
        distinct — DP×FSDP worlds split the global batch over both)."""
        if self.fsdp_axis != self.data_axis:
            return PartitionSpec((self.data_axis, self.fsdp_axis))
        return PartitionSpec(self.data_axis)

    # ---- parameter families (full, unsharded layouts for tp) -------------
    def embedding(self) -> PartitionSpec:
        """[vocab, d_model]: vocab over fsdp, features over tp."""
        return PartitionSpec(self.fsdp_axis, self.tp_axis)

    def qkv(self) -> PartitionSpec:
        """[d_model, heads*d_head]: contraction over fsdp, heads over tp."""
        return PartitionSpec(self.fsdp_axis, self.tp_axis)

    def attn_out(self) -> PartitionSpec:
        """[heads*d_head, d_model]: heads over tp, output over fsdp."""
        return PartitionSpec(self.tp_axis, self.fsdp_axis)

    def mlp_up(self) -> PartitionSpec:
        return PartitionSpec(self.fsdp_axis, self.tp_axis)

    def mlp_down(self) -> PartitionSpec:
        return PartitionSpec(self.tp_axis, self.fsdp_axis)

    def norm(self) -> PartitionSpec:
        """[d_model] scale/bias: replicated (too small to shard)."""
        return PartitionSpec()

    # ---- ZeRO-3 flat shards ---------------------------------------------
    def flat_shard(self) -> PartitionSpec:
        """A ``zero.py`` pad+slice flat leaf ([world*per]) — dim 0 over
        the fsdp axis; the spec of ``_FullZeroState`` array leaves."""
        return PartitionSpec(self.fsdp_axis)

    def replicated(self) -> PartitionSpec:
        return PartitionSpec()


def fsdp_mesh(n_devices: Optional[int] = None, tp: int = 1,
              devices: Optional[Sequence[jax.Device]] = None,
              layout: SpecLayout = SpecLayout(data_axis="fsdp")
              ) -> Tuple[Mesh, SpecLayout]:
    """``(mesh, layout)`` for FSDP(×TP) training: the fsdp axis fills
    what tp leaves over.  The default layout collapses data into fsdp
    (pure ZeRO-3 — every device both batches and shards); pass a layout
    with distinct axes for a 2-D DP×FSDP world built via
    :func:`make_mesh` directly."""
    devs = ordered_devices(devices)
    if n_devices is None:
        n_devices = len(devs)
    if n_devices % tp:
        raise ValueError(f"{n_devices} devices not divisible by tp={tp}")
    axes = {layout.fsdp_axis: n_devices // tp, layout.tp_axis: tp}
    return make_mesh(axes, devs[:n_devices]), layout
