"""Multi-axis mesh construction for dp/tp/sp/ep parallelism.

The reference is data-parallel only (SURVEY.md §2c); its process sets +
alltoall/allgather primitives are the enabling layer for everything else.
Here the enabling layer is mesh-native: a ``jax.sharding.Mesh`` with named
axes, ICI-topology-ordered (``common/topology.py``), over which
``ops/collectives.py`` primitives and the ``parallel/`` schemes compose.

Axis conventions used across the framework:

- ``dp``: data parallel (gradient allreduce — the Horovod axis)
- ``tp``: tensor parallel (Megatron-style sharded matmuls)
- ``sp``: sequence/context parallel (ring attention / Ulysses)
- ``ep``: expert parallel (MoE / DLRM embedding alltoall)
- ``pp``: pipeline stages (microbatched lax.scan pipeline)
"""

from __future__ import annotations

from typing import Dict, Optional, Sequence, Tuple

import jax
import numpy as np
from jax.sharding import Mesh

from ..common.topology import ordered_devices

DP, TP, SP, EP, PP = "dp", "tp", "sp", "ep", "pp"


def make_mesh(axis_sizes: Dict[str, int],
              devices: Optional[Sequence[jax.Device]] = None) -> Mesh:
    """Build a named mesh, e.g. ``make_mesh({"dp": 2, "tp": 2, "sp": 2})``.

    Axis order in the dict is the physical order: the **last** axis varies
    fastest over ICI-neighbor devices, so put the most communication-hungry
    axis (usually ``tp``) last — the standard TPU layout rule (ICI-neighbor
    collectives are cheapest).
    """
    devs = ordered_devices(devices)
    sizes = list(axis_sizes.values())
    total = int(np.prod(sizes))
    if total != len(devs):
        raise ValueError(
            f"Mesh axes {axis_sizes} require {total} devices, have {len(devs)}")
    arr = np.array(devs, dtype=object).reshape(sizes)
    return Mesh(arr, tuple(axis_sizes.keys()))


def infer_mesh(n_devices: int,
               tp: int = 1, sp: int = 1, ep: int = 1, pp: int = 1,
               devices: Optional[Sequence[jax.Device]] = None) -> Mesh:
    """dp fills whatever the fixed axes leave over."""
    denom = tp * sp * ep * pp
    if n_devices % denom:
        raise ValueError(f"{n_devices} devices not divisible by tp*sp*ep*pp={denom}")
    # All axes always present (size-1 axes are free) so PartitionSpecs can
    # reference any of them unconditionally.
    axes = {DP: n_devices // denom, PP: pp, EP: ep, SP: sp, TP: tp}
    return make_mesh(axes, devices)
