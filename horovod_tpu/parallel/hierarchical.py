"""Hierarchical (two-level) allreduce over a (cross, local) mesh.

Parity: the reference's ``HOROVOD_HIERARCHICAL_ALLREDUCE`` path in
``horovod/common/ops/nccl_operations.cc`` (SURVEY.md §2a N17, §2c) — NCCL
ReduceScatter intra-node, MPI allreduce cross-node, NCCL Allgather intra-node.
TPU mapping: ``local`` = ICI within a slice/host, ``cross`` = DCN between
slices.  Same three-phase structure:

    reducescatter(local) -> allreduce(cross) -> allgather(local)

Total bytes over the slow (cross) links drop by a factor of ``local_size``,
which is the entire point when cross rides DCN.
"""

from __future__ import annotations

import jax.numpy as jnp
from jax import lax
from ..compat import axis_size as compat_axis_size


def hierarchical_allreduce(x, cross_axis: str = "cross",
                           local_axis: str = "local",
                           average: bool = False):
    """Two-level allreduce; call inside shard_map over a 2-D mesh."""
    orig_shape, orig_dtype = x.shape, x.dtype
    n_local = compat_axis_size(local_axis)
    flat = x.reshape(-1)
    pad = (-flat.shape[0]) % n_local
    if pad:
        flat = jnp.pad(flat, (0, pad))
    # Phase 1: reduce-scatter across the fast local axis.
    shard = lax.psum_scatter(flat, local_axis, tiled=True)
    # Phase 2: allreduce the 1/n_local shard across the slow cross axis.
    shard = lax.psum(shard, cross_axis)
    # Phase 3: allgather back across the local axis.
    full = lax.all_gather(shard, local_axis, tiled=True)
    if pad:
        full = full[:-pad]
    out = full.reshape(orig_shape)
    if average:
        world = n_local * compat_axis_size(cross_axis)
        out = out / jnp.asarray(world, out.dtype)
    return out.astype(orig_dtype)
