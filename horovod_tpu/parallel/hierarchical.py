"""Hierarchical (two-level) allreduce over a (cross, local) mesh.

Parity: the reference's ``HOROVOD_HIERARCHICAL_ALLREDUCE`` path in
``horovod/common/ops/nccl_operations.cc`` (SURVEY.md §2a N17, §2c) — NCCL
ReduceScatter intra-node, MPI allreduce cross-node, NCCL Allgather intra-node.
TPU mapping: ``local`` = ICI within a slice/host, ``cross`` = DCN between
slices.  Same three-phase structure:

    reducescatter(local) -> allreduce(cross) -> allgather(local)

Total bytes over the slow (cross) links drop by a factor of ``local_size``,
which is the entire point when cross rides DCN.
"""

from __future__ import annotations

import jax.numpy as jnp
from jax import lax
from ..compat import axis_size as compat_axis_size


def hierarchical_allreduce(x, cross_axis: str = "cross",
                           local_axis: str = "local",
                           average: bool = False):
    """Two-level allreduce; call inside shard_map over a 2-D mesh."""
    orig_shape, orig_dtype = x.shape, x.dtype
    n_local = compat_axis_size(local_axis)
    flat = x.reshape(-1)
    pad = (-flat.shape[0]) % n_local
    if pad:
        flat = jnp.pad(flat, (0, pad))
    # Phase 1: reduce-scatter across the fast local axis.
    shard = lax.psum_scatter(flat, local_axis, tiled=True)
    # Phase 2: allreduce the 1/n_local shard across the slow cross axis.
    shard = lax.psum(shard, cross_axis)
    # Phase 3: allgather back across the local axis.
    full = lax.all_gather(shard, local_axis, tiled=True)
    if pad:
        full = full[:-pad]
    out = full.reshape(orig_shape)
    if average:
        world = n_local * compat_axis_size(cross_axis)
        out = out / jnp.asarray(world, out.dtype)
    return out.astype(orig_dtype)


def hierarchical_allreduce_minmax(x, op: str = "min",
                                  cross_axis: str = "cross",
                                  local_axis: str = "local"):
    """Two-level MIN/MAX allreduce; call inside shard_map over a 2-D mesh.

    Same RS→AR→AG shape as the sum path, but min/max have no native
    scatter-reduce: the intra-slice leg gathers over ICI, reduces
    elementwise, and keeps this rank's 1/n_local shard (the same
    construction the engine's flat reducescatter uses for these ops) —
    only that shard crosses DCN via pmin/pmax.  min/max are exact in any
    association order, so results are bitwise-identical to the flat
    pmin/pmax program."""
    if op not in ("min", "max"):
        raise ValueError(f"op must be 'min' or 'max', got {op!r}")
    orig_shape, orig_dtype = x.shape, x.dtype
    n_local = compat_axis_size(local_axis)
    flat = x.reshape(-1)
    pad = (-flat.shape[0]) % n_local
    if pad:
        # Pad with this rank's edge value: min/max over copies of a real
        # element never poisons neighbors, and the pad region is dropped.
        flat = jnp.pad(flat, (0, pad), mode="edge")
    # Phase 1: gather + elementwise reduce + keep our slice (RS-equivalent).
    g = lax.all_gather(flat, local_axis)                 # [n_local, n]
    full = jnp.min(g, axis=0) if op == "min" else jnp.max(g, axis=0)
    chunk = full.shape[0] // n_local
    idx = lax.axis_index(local_axis)
    shard = lax.dynamic_slice_in_dim(full, idx * chunk, chunk, 0)
    # Phase 2: reduce the 1/n_local shard across the slow cross axis.
    shard = (lax.pmin if op == "min" else lax.pmax)(shard, cross_axis)
    # Phase 3: allgather back across the local axis.
    out = lax.all_gather(shard, local_axis, tiled=True)
    if pad:
        out = out[:-pad]
    return out.reshape(orig_shape).astype(orig_dtype)
