"""Ring attention: exact attention over sequence shards via ICI neighbor
exchange.

No reference analogue (Horovod predates sequence parallelism — SURVEY.md §5
explicitly: "ABSENT in the reference"); built on the same primitive class the
reference exposes (point-to-point ring = ``lax.ppermute`` over ICI, the
substrate XLA already provides on the torus).  Algorithm: blockwise/flash
attention with an online-softmax accumulator; K/V blocks rotate around the
``sp`` ring, so each rank sees every block once, overlapping compute with the
neighbor transfer.  Memory per chip stays O(T/sp · T/sp) and the full
sequence is never materialized — the long-context workhorse.

Use inside ``shard_map`` with the sequence dimension sharded over ``sp``:

    out = ring_attention(q, k, v, axis_name="sp", causal=True)

Shapes: q, k, v are the local shards ``[batch, seq_local, heads, head_dim]``.
"""

from __future__ import annotations

from functools import partial
from typing import Optional

import jax
import jax.numpy as jnp
from jax import lax

NEG_INF = -1e30


def _block_attn(q, k, v, bias, scale):
    """One q-block × k-block attention with f32 accumulation.

    Returns (unnormalized out, row max, row sumexp) for online-softmax
    merging.  q: [B,Tq,H,D], k/v: [B,Tk,H,D], bias: [Tq,Tk] or None.
    """
    s = jnp.einsum("bqhd,bkhd->bhqk", q, k,
                   preferred_element_type=jnp.float32) * scale
    if bias is not None:
        s = s + bias[None, None, :, :]
    m = jnp.max(s, axis=-1)                       # [B,H,Tq]
    p = jnp.exp(s - m[..., None])
    l = jnp.sum(p, axis=-1)                       # [B,H,Tq]
    o = jnp.einsum("bhqk,bkhd->bqhd", p.astype(v.dtype), v,
                   preferred_element_type=jnp.float32)
    return o, m, l


def ring_attention(q, k, v, axis_name: str = "sp", causal: bool = False,
                   scale: Optional[float] = None):
    """Exact (flash-equivalent) attention over an ``sp``-sharded sequence."""
    n = lax.axis_size(axis_name)
    my = lax.axis_index(axis_name)
    B, Tq, H, D = q.shape
    Tk = k.shape[1]
    scale = scale if scale is not None else 1.0 / (D ** 0.5)

    # Online-softmax accumulators (f32).
    o_acc = jnp.zeros((B, Tq, H, D), jnp.float32)
    m_acc = jnp.full((B, H, Tq), NEG_INF, jnp.float32)
    l_acc = jnp.zeros((B, H, Tq), jnp.float32)

    shift = [(i, (i + 1) % n) for i in range(n)]

    def merge(carry, block):
        o_acc, m_acc, l_acc = carry
        o, m, l = block
        m_new = jnp.maximum(m_acc, m)
        a = jnp.exp(m_acc - m_new)
        b = jnp.exp(m - m_new)
        l_new = l_acc * a + l * b
        # broadcast [B,H,Tq] -> [B,Tq,H,1]
        a_ = jnp.transpose(a, (0, 2, 1))[..., None]
        b_ = jnp.transpose(b, (0, 2, 1))[..., None]
        o_new = o_acc * a_ + o.astype(jnp.float32) * b_
        return o_new, m_new, l_new

    kv = (k, v)
    for step in range(n):
        src = (my - step) % n          # which rank's K/V block we now hold
        k_cur, v_cur = kv
        if causal:
            def compute(args):
                q_, k_, v_ = args
                q_pos = my * Tq + jnp.arange(Tq)
                k_pos = src * Tk + jnp.arange(Tk)
                bias = jnp.where(q_pos[:, None] >= k_pos[None, :],
                                 0.0, NEG_INF)
                return _block_attn(q_, k_, v_, bias, scale)

            def masked(args):
                # Identity element of the online-softmax merge.
                return (jnp.zeros((B, Tq, H, D), jnp.float32),
                        jnp.full((B, H, Tq), NEG_INF, jnp.float32),
                        jnp.zeros((B, H, Tq), jnp.float32))

            # src = (my-step)%n > my  ⇔  my < step: this rank's queries are
            # entirely BEFORE the held block — skip the whole block's
            # compute (≈ halves the causal ring's FLOPs at large sp).
            o, m, l = lax.cond(my < step, masked, compute, (q, k_cur, v_cur))
        else:
            o, m, l = _block_attn(q, k_cur, v_cur, None, scale)
        o_acc, m_acc, l_acc = merge((o_acc, m_acc, l_acc), (o, m, l))
        if step != n - 1:
            # Rotate K/V to the next rank; XLA overlaps this with compute.
            kv = (lax.ppermute(k_cur, axis_name, perm=shift),
                  lax.ppermute(v_cur, axis_name, perm=shift))

    l_ = jnp.transpose(l_acc, (0, 2, 1))[..., None]        # [B,Tq,H,1]
    out = o_acc / jnp.maximum(l_, 1e-30)
    return out.astype(q.dtype)


def local_flash_attention(q, k, v, causal: bool = False,
                          scale: Optional[float] = None):
    """Single-device reference attention (same math, no ring) for tests and
    for the sp=1 fast path.  GQA is native: kv may have ``K = H / rep``
    heads — a grouped einsum, no HBM repeat."""
    B, Tq, H, D = q.shape
    K = k.shape[2]
    scale = scale if scale is not None else 1.0 / (D ** 0.5)
    if K != H:
        if v.shape[2] != K or H % K:
            raise ValueError(f"GQA heads mismatch: q={H} k={K} v={v.shape[2]}")
        qg = q.reshape(B, Tq, K, H // K, D)
        s = jnp.einsum("bqkrd,bskd->bkrqs", qg, k,
                       preferred_element_type=jnp.float32) * scale
        if causal:
            Tk = k.shape[1]
            mask = jnp.arange(Tq)[:, None] >= jnp.arange(Tk)[None, :]
            s = jnp.where(mask[None, None, None], s, NEG_INF)
        p = jax.nn.softmax(s, axis=-1)
        out = jnp.einsum("bkrqs,bskd->bqkrd", p.astype(v.dtype), v,
                         preferred_element_type=jnp.float32)
        return out.reshape(B, Tq, H, D).astype(q.dtype)
    s = jnp.einsum("bqhd,bkhd->bhqk", q, k,
                   preferred_element_type=jnp.float32) * scale
    if causal:
        Tk = k.shape[1]
        mask = jnp.arange(Tq)[:, None] >= jnp.arange(Tk)[None, :]
        s = jnp.where(mask[None, None], s, NEG_INF)
    p = jax.nn.softmax(s, axis=-1)
    return jnp.einsum("bhqk,bkhd->bqhd", p.astype(v.dtype), v,
                      preferred_element_type=jnp.float32).astype(q.dtype)
