"""Ring attention: exact attention over sequence shards via ICI neighbor
exchange.

No reference analogue (Horovod predates sequence parallelism — SURVEY.md §5
explicitly: "ABSENT in the reference"); built on the same primitive class the
reference exposes (point-to-point ring = ``lax.ppermute`` over ICI, the
substrate XLA already provides on the torus).  Algorithm: blockwise/flash
attention with an online-softmax accumulator; K/V blocks rotate around the
``sp`` ring, so each rank sees every block once, overlapping compute with the
neighbor transfer.  Memory per chip stays O(T/sp · T/sp) and the full
sequence is never materialized — the long-context workhorse.

Use inside ``shard_map`` with the sequence dimension sharded over ``sp``:

    out = ring_attention(q, k, v, axis_name="sp", causal=True)

Shapes: q, k, v are the local shards ``[batch, seq_local, heads, head_dim]``.
"""

from __future__ import annotations

from functools import partial
from typing import Optional

import jax
import jax.numpy as jnp
from jax import lax
from ..compat import axis_size as compat_axis_size

NEG_INF = -1e30


def _block_attn(q, k, v, bias, scale):
    """One q-block × k-block attention with f32 accumulation.

    Returns (unnormalized out, row max, row sumexp) for online-softmax
    merging.  q: [B,Tq,H,D], k/v: [B,Tk,H,D], bias: [Tq,Tk] or None.
    """
    s = jnp.einsum("bqhd,bkhd->bhqk", q, k,
                   preferred_element_type=jnp.float32) * scale
    if bias is not None:
        s = s + bias[None, None, :, :]
    m = jnp.max(s, axis=-1)                       # [B,H,Tq]
    p = jnp.exp(s - m[..., None])
    l = jnp.sum(p, axis=-1)                       # [B,H,Tq]
    o = jnp.einsum("bhqk,bkhd->bqhd", p.astype(v.dtype), v,
                   preferred_element_type=jnp.float32)
    return o, m, l


def ring_attention(q, k, v, axis_name: str = "sp", causal: bool = False,
                   scale: Optional[float] = None,
                   use_flash: Optional[bool] = None,
                   block_q: Optional[int] = None,
                   block_k: Optional[int] = None,
                   interpret: Optional[bool] = None):
    """Exact (flash-equivalent) attention over an ``sp``-sharded sequence.

    q: ``[B, T_loc, H, D]``; k, v: ``[B, T_loc, K, D]`` with ``H % K == 0``
    — GQA is supported on both paths (the pallas path reads shared kv heads
    natively, so the ring rotates ``H/K``× less data than a materialized
    repeat would).

    Two inner engines, same numerics:

    - **Pallas flash** (default on TPU; forced by ``use_flash=True`` or
      ``HVD_TPU_FLASH=1`` — interpret mode off-TPU): every per-block
      (o, lse) pair comes from the flash kernels in
      ``ops/flash_attention.py``; ring steps merge the normalized pairs by
      logsumexp weighting, and a custom VJP runs the backward ring over the
      flash backward kernels with the GLOBAL lse (dq rides the rotating
      tuple back to its owner; dk/dv accumulate where the kv shard lives).
    - **jnp blockwise** (fallback): the original online-softmax ring.
    """
    from ..ops.flash_attention import (resolve_flash, _interpret_default,
                                       resolve_blocks)
    # No seq threshold here: the alternative to the pallas ring engine is
    # the jnp blockwise ring below (full per-step [B,H,Tq,Tk] scores in
    # HBM + a materialized GQA repeat), NOT XLA's fused single-device
    # attention — so the single-device crossover (flash_min_seq) does not
    # apply and TPU auto mode always takes the flash engine.
    if resolve_flash(use_flash):
        if interpret is None:
            interpret = _interpret_default()
        block_q, block_k = resolve_blocks(block_q, block_k)
        return _ring_flash_bthd(q, k, v, axis_name, causal, scale,
                                block_q, block_k, interpret)
    if k.shape[2] != q.shape[2]:
        # jnp path's accumulator is head-aligned: materialize the GQA
        # repeat (the pallas path above avoids this).
        rep = q.shape[2] // k.shape[2]
        k = jnp.repeat(k, rep, axis=2)
        v = jnp.repeat(v, rep, axis=2)
    n = compat_axis_size(axis_name)
    my = lax.axis_index(axis_name)
    B, Tq, H, D = q.shape
    Tk = k.shape[1]
    scale = scale if scale is not None else 1.0 / (D ** 0.5)

    # Online-softmax accumulators (f32).
    o_acc = jnp.zeros((B, Tq, H, D), jnp.float32)
    m_acc = jnp.full((B, H, Tq), NEG_INF, jnp.float32)
    l_acc = jnp.zeros((B, H, Tq), jnp.float32)

    shift = [(i, (i + 1) % n) for i in range(n)]

    def merge(carry, block):
        o_acc, m_acc, l_acc = carry
        o, m, l = block
        m_new = jnp.maximum(m_acc, m)
        a = jnp.exp(m_acc - m_new)
        b = jnp.exp(m - m_new)
        l_new = l_acc * a + l * b
        # broadcast [B,H,Tq] -> [B,Tq,H,1]
        a_ = jnp.transpose(a, (0, 2, 1))[..., None]
        b_ = jnp.transpose(b, (0, 2, 1))[..., None]
        o_new = o_acc * a_ + o.astype(jnp.float32) * b_
        return o_new, m_new, l_new

    kv = (k, v)
    for step in range(n):
        src = (my - step) % n          # which rank's K/V block we now hold
        k_cur, v_cur = kv
        if causal:
            def compute(args):
                q_, k_, v_ = args
                q_pos = my * Tq + jnp.arange(Tq)
                k_pos = src * Tk + jnp.arange(Tk)
                bias = jnp.where(q_pos[:, None] >= k_pos[None, :],
                                 0.0, NEG_INF)
                return _block_attn(q_, k_, v_, bias, scale)

            def masked(args):
                # Identity element of the online-softmax merge.
                return (jnp.zeros((B, Tq, H, D), jnp.float32),
                        jnp.full((B, H, Tq), NEG_INF, jnp.float32),
                        jnp.zeros((B, H, Tq), jnp.float32))

            # src = (my-step)%n > my  ⇔  my < step: this rank's queries are
            # entirely BEFORE the held block — skip the whole block's
            # compute (≈ halves the causal ring's FLOPs at large sp).
            o, m, l = lax.cond(my < step, masked, compute, (q, k_cur, v_cur))
        else:
            o, m, l = _block_attn(q, k_cur, v_cur, None, scale)
        o_acc, m_acc, l_acc = merge((o_acc, m_acc, l_acc), (o, m, l))
        if step != n - 1:
            # Rotate K/V to the next rank; XLA overlaps this with compute.
            kv = (lax.ppermute(k_cur, axis_name, perm=shift),
                  lax.ppermute(v_cur, axis_name, perm=shift))

    l_ = jnp.transpose(l_acc, (0, 2, 1))[..., None]        # [B,Tq,H,1]
    out = o_acc / jnp.maximum(l_, 1e-30)
    return out.astype(q.dtype)


# ------------------------------------------------- pallas-flash ring engine
def _ring_flash_bthd(q, k, v, axis_name, causal, scale, block_q, block_k,
                     interpret):
    """[B, T, H, D] wrapper: flatten heads into the batch dim ([BH, T, D],
    the flash kernels' layout), run the flash ring core, restore."""
    B, Tq, H, D = q.shape
    K = k.shape[2]
    if v.shape[2] != K or (K != H and H % K):
        raise ValueError(f"GQA heads mismatch: q={H} k={K} v={v.shape[2]}")
    rep = H // K
    scale = scale if scale is not None else 1.0 / (D ** 0.5)

    def to_bh(x):
        h = x.shape[2]
        return x.transpose(0, 2, 1, 3).reshape(B * h, x.shape[1], D)

    o = _ring_flash_core(to_bh(q), to_bh(k), to_bh(v), axis_name, causal,
                         scale, block_q, block_k, interpret, rep)
    return o.reshape(B, H, Tq, D).transpose(0, 2, 1, 3)


def _ring_flash_forward(qb, kb, vb, axis_name, causal, scale, block_q,
                        block_k, interpret, rep):
    """Forward ring over the flash forward kernel.  Per step the held kv
    block is one of three STATIC cases (step is a Python int, so the kernel
    config stays static): step 0 = the causal diagonal; step > 0 = full
    block when this rank's queries are after the held kv (my >= step),
    identity otherwise.  Normalized per-block (o, lse) pairs merge by
    logsumexp weighting.  Returns (o [BH, Tq, D] in q dtype, global lse)."""
    from ..ops.flash_attention import _fwd_impl
    n = compat_axis_size(axis_name)
    my = lax.axis_index(axis_name)
    BH, Tq, D = qb.shape
    o_acc = jnp.zeros((BH, Tq, D), jnp.float32)
    lse_acc = jnp.full((BH, Tq), NEG_INF, jnp.float32)
    shift = [(i, (i + 1) % n) for i in range(n)]

    kv = (kb, vb)
    for step in range(n):
        k_cur, v_cur = kv
        if step == 0:
            o_i, lse_i = _fwd_impl(qb, k_cur, v_cur, scale, causal,
                                   block_q, block_k, interpret, rep)
            o_i = o_i.astype(jnp.float32)
        elif causal:
            def compute(args):
                q_, k_, v_ = args
                o_c, l_c = _fwd_impl(q_, k_, v_, scale, False,
                                     block_q, block_k, interpret, rep)
                return o_c.astype(jnp.float32), l_c

            def masked(args):
                # Identity of the (o, lse) merge.
                return (jnp.zeros((BH, Tq, D), jnp.float32),
                        jnp.full((BH, Tq), NEG_INF, jnp.float32))

            o_i, lse_i = lax.cond(my < step, masked, compute,
                                  (qb, k_cur, v_cur))
        else:
            o_i, lse_i = _fwd_impl(qb, k_cur, v_cur, scale, False,
                                   block_q, block_k, interpret, rep)
            o_i = o_i.astype(jnp.float32)
        lse_new = jnp.logaddexp(lse_acc, lse_i)
        a = jnp.exp(lse_acc - lse_new)[..., None]
        b = jnp.exp(lse_i - lse_new)[..., None]
        o_acc = o_acc * a + o_i * b
        lse_acc = lse_new
        if step != n - 1:
            kv = (lax.ppermute(k_cur, axis_name, perm=shift),
                  lax.ppermute(v_cur, axis_name, perm=shift))
    return o_acc.astype(qb.dtype), lse_acc


@partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6, 7, 8, 9))
def _ring_flash_core(qb, kb, vb, axis_name, causal, scale, block_q, block_k,
                     interpret, rep):
    o, _ = _ring_flash_forward(qb, kb, vb, axis_name, causal, scale,
                               block_q, block_k, interpret, rep)
    return o


def _ring_flash_fwd_rule(qb, kb, vb, axis_name, causal, scale, block_q,
                         block_k, interpret, rep):
    o, lse = _ring_flash_forward(qb, kb, vb, axis_name, causal, scale,
                                 block_q, block_k, interpret, rep)
    return o, (qb, kb, vb, o, lse)


def _ring_flash_bwd_rule(axis_name, causal, scale, block_q, block_k,
                         interpret, rep, res, do):
    """Backward ring: kv (and its dk/dv accumulators) stay put; the tuple
    (q, do, lse, delta, dq) rotates.  At step t the held q belongs to rank
    ``(my - t) % n``; with causal masking it attends this rank's kv iff
    my < t (plus the t = 0 diagonal).  Every step uses the flash backward
    kernels with the GLOBAL lse/delta, so per-pair contributions are exact;
    after n rotations the dq accumulator arrives back at its owner."""
    from ..ops.flash_attention import _bwd_impl
    qb, kb, vb, o, lse = res
    n = compat_axis_size(axis_name)
    my = lax.axis_index(axis_name)
    BH, Tq, D = qb.shape
    delta = jnp.sum(do.astype(jnp.float32) * o.astype(jnp.float32), axis=-1)
    shift = [(i, (i + 1) % n) for i in range(n)]

    dk_acc = jnp.zeros(kb.shape, jnp.float32)
    dv_acc = jnp.zeros(vb.shape, jnp.float32)
    rot = (qb, do, lse, delta, jnp.zeros((BH, Tq, D), jnp.float32))
    for t in range(n):
        q_t, do_t, lse_t, delta_t, dq_t = rot
        if t == 0:
            dq_i, dk_i, dv_i = _bwd_impl(
                q_t, kb, vb, do_t, lse_t, delta_t, scale=scale,
                causal=causal, block_q=block_q, block_k=block_k,
                interpret=interpret, rep=rep)
        elif causal:
            def compute(args):
                q_, do_, lse_, delta_ = args
                return _bwd_impl(q_, kb, vb, do_, lse_, delta_, scale=scale,
                                 causal=False, block_q=block_q,
                                 block_k=block_k, interpret=interpret,
                                 rep=rep)

            def skip(args):
                return (jnp.zeros((BH, Tq, D), qb.dtype),
                        jnp.zeros(kb.shape, kb.dtype),
                        jnp.zeros(vb.shape, vb.dtype))

            dq_i, dk_i, dv_i = lax.cond(my < t, compute, skip,
                                        (q_t, do_t, lse_t, delta_t))
        else:
            dq_i, dk_i, dv_i = _bwd_impl(
                q_t, kb, vb, do_t, lse_t, delta_t, scale=scale,
                causal=False, block_q=block_q, block_k=block_k,
                interpret=interpret, rep=rep)
        dk_acc = dk_acc + dk_i.astype(jnp.float32)
        dv_acc = dv_acc + dv_i.astype(jnp.float32)
        rot = (q_t, do_t, lse_t, delta_t, dq_t + dq_i.astype(jnp.float32))
        # Rotate every step (including the last) so each tuple lands back
        # on its owner after n hops.
        rot = tuple(lax.ppermute(x, axis_name, perm=shift) for x in rot)
    dq_home = rot[4]
    return (dq_home.astype(qb.dtype), dk_acc.astype(kb.dtype),
            dv_acc.astype(vb.dtype))


_ring_flash_core.defvjp(_ring_flash_fwd_rule, _ring_flash_bwd_rule)


def _causal_mask(Tq, Tk, window: Optional[int]):
    m = jnp.arange(Tq)[:, None] >= jnp.arange(Tk)[None, :]
    if window:
        m = jnp.logical_and(
            m, (jnp.arange(Tq)[:, None] - jnp.arange(Tk)[None, :])
            < window)
    return m


def local_flash_attention(q, k, v, causal: bool = False,
                          scale: Optional[float] = None,
                          window: Optional[int] = None):
    """Single-device reference attention (same math, no ring) for tests and
    for the sp=1 fast path.  GQA is native: kv may have ``K = H / rep``
    heads — a grouped einsum, no HBM repeat.  ``window`` = sliding-window
    (Mistral-style) causal attention over the last ``window`` positions."""
    B, Tq, H, D = q.shape
    K = k.shape[2]
    scale = scale if scale is not None else 1.0 / (D ** 0.5)
    if window is not None and not causal:
        raise ValueError("window requires causal=True")
    if K != H:
        if v.shape[2] != K or H % K:
            raise ValueError(f"GQA heads mismatch: q={H} k={K} v={v.shape[2]}")
        qg = q.reshape(B, Tq, K, H // K, D)
        s = jnp.einsum("bqkrd,bskd->bkrqs", qg, k,
                       preferred_element_type=jnp.float32) * scale
        if causal:
            mask = _causal_mask(Tq, k.shape[1], window)
            s = jnp.where(mask[None, None, None], s, NEG_INF)
        p = jax.nn.softmax(s, axis=-1)
        out = jnp.einsum("bkrqs,bskd->bqkrd", p.astype(v.dtype), v,
                         preferred_element_type=jnp.float32)
        return out.reshape(B, Tq, H, D).astype(q.dtype)
    s = jnp.einsum("bqhd,bkhd->bhqk", q, k,
                   preferred_element_type=jnp.float32) * scale
    if causal:
        mask = _causal_mask(Tq, k.shape[1], window)
        s = jnp.where(mask[None, None], s, NEG_INF)
    p = jax.nn.softmax(s, axis=-1)
    return jnp.einsum("bhqk,bkhd->bqhd", p.astype(v.dtype), v,
                      preferred_element_type=jnp.float32).astype(q.dtype)
