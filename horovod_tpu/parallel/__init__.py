"""Parallelism strategies beyond data-parallel (SURVEY.md §2c): sequence
parallelism (ring + Ulysses), pipeline parallelism, Adasum, hierarchical
two-level collectives, ZeRO-sharded optimizers, and the mesh/SPMD helpers
that tie them to ``jax.sharding``."""

from .mesh import DP, TP, SP, EP, PP, infer_mesh, make_mesh  # noqa: F401
from .spmd import (  # noqa: F401
    infer_specs_like, make_sharded_train_step, shard_params,
)
from .ring_attention import (  # noqa: F401
    local_flash_attention, ring_attention,
)
from .ulysses import heads_to_seq, seq_to_heads, ulysses_attention  # noqa: F401
from .pipeline import microbatch, pipeline_apply  # noqa: F401
from .adasum import (  # noqa: F401
    adasum_allreduce, adasum_allreduce_hd, adasum_allreduce_hier,
    adasum_combine, torus_bit_order,
)
from .hierarchical import (  # noqa: F401
    hierarchical_allreduce, hierarchical_allreduce_minmax,
)
from .topology import (  # noqa: F401
    SliceTopology, cross_fraction, hier_bit_orders, modeled_leg_bytes,
    parse_slice_map, slice_topology,
)
from .mesh import (  # noqa: F401
    SpecLayout, fsdp_mesh, process_set_mesh, process_set_sharding,
    process_set_spec,
)
from .zero import (  # noqa: F401
    full_sharded_optimizer, gather_full_params, init_full_sharded_state,
    init_sharded_state, shard_info, shard_slice_host, sharded_optimizer,
    state_specs, unshard_host,
)
