"""Harness for jitting explicit-SPMD train steps over a multi-axis mesh.

Glue between the model zoo (``models/``) and the mesh layer: given a model's
param PartitionSpecs and a per-shard train step (written with explicit
collectives — the framework's TPU-native style), produce the compiled
multi-chip program via ``shard_map`` + ``jit``.

The reference has no counterpart (its unit of execution is a single-GPU
framework graph + out-of-graph collectives); this module is where the
rebuild exploits XLA's whole-program compilation instead.
"""

from __future__ import annotations

from typing import Any, Callable

import jax
import numpy as np
from ..compat import shard_map
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P


def infer_specs_like(tree, params, param_specs) -> Any:
    """PartitionSpecs for an arbitrary pytree (e.g. optax state).

    Optax states embed whole subtrees with the params' exact tree structure
    (mu/nu/trace); those get the params' spec tree verbatim.  Everything
    else (step counters, scalars, unrecognized leaves) is replicated (P()),
    which is always correct, just not sharded.  Structure matching — not
    shape matching — because two params can share a shape but differ in
    sharding (e.g. a column-parallel wq and row-parallel wo of equal size).
    """
    p_leaves, params_struct = jax.tree_util.tree_flatten(params)
    p_shapes = [tuple(l.shape) for l in p_leaves]

    def is_param_tree(sub) -> bool:
        # Structure AND leaf-shape equality: structure alone degenerates for
        # single-array params (any scalar leaf matches a one-leaf treedef).
        try:
            leaves, struct = jax.tree_util.tree_flatten(sub)
            return (struct == params_struct
                    and [tuple(getattr(l, "shape", ())) for l in leaves]
                    == p_shapes)
        except Exception:
            return False

    return jax.tree_util.tree_map(
        lambda s: param_specs if is_param_tree(s) else P(),
        tree, is_leaf=is_param_tree)


def shard_params(params, param_specs, mesh: Mesh):
    """Place a host-side param pytree onto the mesh per its specs."""
    def put(p, spec):
        return jax.device_put(p, NamedSharding(mesh, spec))
    return jax.tree_util.tree_map(put, params, param_specs,
                                  is_leaf=lambda x: hasattr(x, "shape"))


def _spec_axes(spec_trees) -> set:
    """Every mesh axis the given PartitionSpec trees shard over — the
    declared partition axes handed to the trace checker's HVD112 pass."""
    axes = set()
    leaves = jax.tree_util.tree_leaves(
        spec_trees, is_leaf=lambda x: isinstance(x, P))
    for leaf in leaves:
        if not isinstance(leaf, P):
            continue
        for entry in leaf:
            if isinstance(entry, str):
                axes.add(entry)
            elif isinstance(entry, (tuple, list)):
                axes.update(a for a in entry if isinstance(a, str))
    return axes


def make_sharded_train_step(step_fn: Callable, mesh: Mesh,
                            param_specs, opt_state_specs,
                            data_spec, check=False) -> Callable:
    """Compile ``step_fn(params, opt_state, tokens, targets)`` over the mesh.

    ``step_fn`` is per-shard (explicit collectives inside); in/out specs:
    params+opt_state per their spec trees, data per ``data_spec``, loss
    replicated.

    ``check=True`` runs :func:`analysis.trace_check.check_step_fn` over the
    step at trace time (the first call, abstractly — nothing executes) and
    logs any HVD2xx findings; ``check="strict"`` raises on error findings
    instead.  This is the jaxpr twin of the optimizers' ``check=`` lint
    hook: unknown axes, bad ``axis_index_groups``, non-bijective ppermute
    perms and host callbacks are caught before the program ever reaches a
    pod, where they would deadlock instead of erroring.

    **Sharded (ZeRO) optimizer states** (ISSUE 15): a step built around
    ``parallel.zero.sharded_optimizer`` holds 1/world of the optimizer
    state per device — its leaves are rank-DISTINCT, so ``opt_state_specs``
    must shard them over the dp axis, never replicate.  Build both the
    state and its spec tree with ``parallel.zero.init_sharded_state``
    (or derive specs from an existing state with
    ``parallel.zero.state_specs``) and pass the specs here; ``P()``-style
    replication of a sharded state is undefined behavior (each device
    holds a different shard).
    """
    sharded = shard_map(
        step_fn, mesh=mesh,
        in_specs=(param_specs, opt_state_specs, data_spec, data_spec),
        out_specs=(param_specs, opt_state_specs, P()),
        check_vma=False)
    jitted = jax.jit(sharded, donate_argnums=(0, 1))
    if not check:
        return jitted

    from ..analysis import trace_check
    from ..utils.logging import get_logger
    checked = []
    # The axes the step's partition specs actually shard over: a traced
    # collective reducing over a mesh axis OUTSIDE this set runs over
    # replicated data (the fsdp × tp mismatch) — trace_check flags it as
    # HVD112, the jaxpr twin of collective_lint's AST check.
    declared = _spec_axes((param_specs, opt_state_specs, data_spec))

    def checking_step(params, opt_state, tokens, targets):
        if not checked:
            checked.append(True)
            report = trace_check.check_step_fn(
                sharded, params, opt_state, tokens, targets, mesh=mesh,
                partition_axes=sorted(declared) if declared else None,
                path="<make_sharded_train_step>")
            errors = [f for f in report.findings if f.is_error]
            if errors and check == "strict":
                raise RuntimeError(
                    "make_sharded_train_step(check='strict'): the traced "
                    "step failed the collective audit:\n"
                    + "\n".join(f.render() for f in errors))
            for f in report.findings:
                get_logger().warning("trace check: %s", f.render())
        return jitted(params, opt_state, tokens, targets)

    return checking_step
