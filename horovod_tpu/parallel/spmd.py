"""Harness for jitting explicit-SPMD train steps over a multi-axis mesh.

Glue between the model zoo (``models/``) and the mesh layer: given a model's
param PartitionSpecs and a per-shard train step (written with explicit
collectives — the framework's TPU-native style), produce the compiled
multi-chip program via ``shard_map`` + ``jit``.

The reference has no counterpart (its unit of execution is a single-GPU
framework graph + out-of-graph collectives); this module is where the
rebuild exploits XLA's whole-program compilation instead.
"""

from __future__ import annotations

from typing import Any, Callable, Dict, Tuple

import jax
import numpy as np
from jax import shard_map
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P


def infer_specs_like(tree, params, param_specs) -> Any:
    """PartitionSpecs for an arbitrary pytree (e.g. optax state) by shape-
    matching its array leaves against the params' specs.

    Optax states are pytrees whose array leaves either mirror a param
    (mu/nu/trace — same shape, same sharding) or are scalars/step counters
    (replicated).  Shapes that never appear among params get P() —
    replicated — which is always correct, just not sharded.
    """
    shape_to_spec: Dict[Tuple, Any] = {}
    p_leaves = jax.tree_util.tree_leaves(params)
    s_leaves = jax.tree_util.tree_leaves(
        param_specs, is_leaf=lambda x: isinstance(x, P))
    for pl, sl in zip(p_leaves, s_leaves):
        shape_to_spec.setdefault(tuple(pl.shape), sl)

    def leaf_spec(leaf):
        shape = tuple(getattr(leaf, "shape", ()))
        return shape_to_spec.get(shape, P())

    return jax.tree_util.tree_map(leaf_spec, tree)


def shard_params(params, param_specs, mesh: Mesh):
    """Place a host-side param pytree onto the mesh per its specs."""
    def put(p, spec):
        return jax.device_put(p, NamedSharding(mesh, spec))
    return jax.tree_util.tree_map(put, params, param_specs,
                                  is_leaf=lambda x: hasattr(x, "shape"))


def make_sharded_train_step(step_fn: Callable, mesh: Mesh,
                            param_specs, opt_state_specs,
                            data_spec) -> Callable:
    """Compile ``step_fn(params, opt_state, tokens, targets)`` over the mesh.

    ``step_fn`` is per-shard (explicit collectives inside); in/out specs:
    params+opt_state per their spec trees, data per ``data_spec``, loss
    replicated.
    """
    sharded = shard_map(
        step_fn, mesh=mesh,
        in_specs=(param_specs, opt_state_specs, data_spec, data_spec),
        out_specs=(param_specs, opt_state_specs, P()),
        check_vma=False)
    return jax.jit(sharded, donate_argnums=(0, 1))
