"""Adasum: adaptive-summation gradient reduction on the TPU torus.

TPU-native equivalent of the reference's Adasum ops
(``horovod/common/ops/adasum/adasum.h``, ``adasum_mpi_operations.cc``,
``adasum_gpu_operations.cc`` — SURVEY.md §2a N20).  Adasum combines two
gradients by subtracting the mutual projections so the result is
scale-invariant when the gradients are correlated:

    adasum(a, b) = (1 - a.b / (2|a|^2)) a + (1 - a.b / (2|b|^2)) b

and reduces n ranks by applying this pairwise in a binary tree — the same
combination order as the reference's recursive vector-halving-doubling, so
numerics match rank-for-rank.

Two implementations:

- ``adasum_allreduce``: all_gather + in-register tree combine.  Simple and
  XLA-friendly; bandwidth cost n·|x| over ICI (fine up to moderate world
  sizes, and XLA overlaps the gather with compute).
- ``adasum_allreduce_hd``: true vector-halving-doubling over
  ``lax.ppermute`` — log2(n) rounds, each exchanging half the remaining
  vector with a partner at distance 2^k, mirroring the reference's MPI
  algorithm but riding ICI neighbor links.  Requires power-of-two world.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax


def _dots(a, b):
    """Returns (a.b, |a|^2, |b|^2) computed in f32 over flattened tensors."""
    af = a.astype(jnp.float32).reshape(-1)
    bf = b.astype(jnp.float32).reshape(-1)
    return af @ bf, af @ af, bf @ bf


def adasum_combine(a, b, eps: float = 1e-30):
    """Pairwise Adasum of two same-shaped tensors.

    Orthogonal gradients (a.b = 0) sum exactly; parallel gradients average,
    giving scale-invariance — the property the reference's
    ``docs/adasum_user_guide`` advertises.
    """
    ab, aa, bb = _dots(a, b)
    ca = 1.0 - ab / (2.0 * aa + eps)
    cb = 1.0 - ab / (2.0 * bb + eps)
    out = (ca.astype(jnp.float32) * a.astype(jnp.float32)
           + cb.astype(jnp.float32) * b.astype(jnp.float32))
    return out.astype(a.dtype)


def _tree_reduce(stack, n):
    """Binary-tree pairwise adasum over a gathered [n, ...] stack.

    Tree pairing (0,1),(2,3),... per level reproduces the reference's
    halving-doubling combination order.  Non-power-of-two remainders are
    folded in at each level, as the reference's VHDD remainder step does.
    """
    vals = [stack[i] for i in range(n)]
    while len(vals) > 1:
        nxt = []
        for i in range(0, len(vals) - 1, 2):
            nxt.append(adasum_combine(vals[i], vals[i + 1]))
        if len(vals) % 2 == 1:
            nxt[-1] = adasum_combine(nxt[-1], vals[-1])
        vals = nxt
    return vals[0]


def adasum_allreduce(x, axis_name="hvd"):
    """Adasum allreduce usable inside shard_map/jit (any world size)."""
    n = lax.axis_size(axis_name)
    g = lax.all_gather(x, axis_name)  # [n, ...]
    return _tree_reduce(g, n)


def adasum_allreduce_hd(x, axis_name="hvd"):
    """Vector-halving-doubling Adasum via ppermute (power-of-two worlds).

    Round k: partner = rank XOR 2^k.  Each rank sends the half of its
    working vector that the partner owns, receives the partner's half of its
    own, combines with adasum, and recurses on its half; then the doubling
    phase allgathers the combined halves back.  This is the reference
    ``adasum_mpi.cc`` algorithm with MPI_Sendrecv replaced by
    lax.ppermute pairs over ICI.
    """
    n = lax.axis_size(axis_name)
    # Static world size: shard_map gives a concrete int at trace time.
    n_static = int(n) if not isinstance(n, int) else n
    if n_static & (n_static - 1):
        raise ValueError("adasum_allreduce_hd requires power-of-two world size; "
                         "use adasum_allreduce instead")
    orig_shape, orig_dtype = x.shape, x.dtype
    flat = x.astype(jnp.float32).reshape(-1)
    pad = (-flat.shape[0]) % n_static
    flat = jnp.pad(flat, (0, pad))
    rank = lax.axis_index(axis_name)

    # Halving phase: at each round exchange opposite halves with the partner.
    segments = flat  # this rank's current working segment
    rounds = n_static.bit_length() - 1
    for k in range(rounds):
        dist = 1 << k
        perm = [(i, i ^ dist) for i in range(n_static)]
        half = segments.shape[0] // 2
        low, high = segments[:half], segments[half:]
        # Ranks where bit k is 0 keep the low half and send the high; bit 1
        # keeps high, sends low.
        to_send = lax.cond(((rank >> k) & 1) == 0, lambda: high, lambda: low)
        received = lax.ppermute(to_send, axis_name, perm=perm)
        kept = lax.cond(((rank >> k) & 1) == 0, lambda: low, lambda: high)
        segments = adasum_combine(kept, received)

    # Doubling phase: allgather the 1/n segments in rank order.
    gathered = lax.all_gather(segments, axis_name)  # [n, chunk]
    # Rank r holds the segment whose index is bit-reversal-free: the kept
    # segment of rank r is the one starting at offset determined by its bits.
    # Reconstruct by computing each rank's segment start.
    chunk = segments.shape[0]
    starts = []
    for r in range(n_static):
        start = 0
        span = n_static
        for k in range(rounds):
            span //= 2
            if (r >> k) & 1:
                start += span
            # start tracks which final chunk this rank's segment begins at
        starts.append(start)
    order = [0] * n_static
    for r, s in enumerate(starts):
        order[s] = r
    full = jnp.concatenate([gathered[order[i]] for i in range(n_static)])
    if pad:
        full = full[:-pad]
    return full.reshape(orig_shape).astype(orig_dtype)
