"""Adasum: adaptive-summation gradient reduction on the TPU torus.

TPU-native equivalent of the reference's Adasum ops
(``horovod/common/ops/adasum/adasum.h``, ``adasum_mpi_operations.cc``,
``adasum_gpu_operations.cc`` — SURVEY.md §2a N20).  Adasum combines two
gradients by subtracting the mutual projections so the result is
scale-invariant when the gradients are correlated:

    adasum(a, b) = (1 - a.b / (2|a|^2)) a + (1 - a.b / (2|b|^2)) b

and reduces n ranks by applying this pairwise in a binary tree — the same
combination order as the reference's recursive vector-halving-doubling, so
numerics match rank-for-rank.

Two implementations:

- ``adasum_allreduce``: all_gather + in-register tree combine.  Simple and
  XLA-friendly; bandwidth cost n·|x| over ICI (fine up to moderate world
  sizes, and XLA overlaps the gather with compute).
- ``adasum_allreduce_hd``: true vector-halving-doubling over
  ``lax.ppermute`` — log2(n) rounds, each exchanging half the remaining
  vector with a partner at distance 2^k, mirroring the reference's MPI
  algorithm but riding ICI neighbor links.  Requires power-of-two world.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax
from ..compat import axis_size as compat_axis_size


def _dots(a, b):
    """Returns (a.b, |a|^2, |b|^2) computed in f32 over flattened tensors."""
    af = a.astype(jnp.float32).reshape(-1)
    bf = b.astype(jnp.float32).reshape(-1)
    return af @ bf, af @ af, bf @ bf


def adasum_combine(a, b, eps: float = 1e-30):
    """Pairwise Adasum of two same-shaped tensors.

    Orthogonal gradients (a.b = 0) sum exactly; parallel gradients average,
    giving scale-invariance — the property the reference's
    ``docs/adasum_user_guide`` advertises.
    """
    ab, aa, bb = _dots(a, b)
    ca = 1.0 - ab / (2.0 * aa + eps)
    cb = 1.0 - ab / (2.0 * bb + eps)
    out = (ca.astype(jnp.float32) * a.astype(jnp.float32)
           + cb.astype(jnp.float32) * b.astype(jnp.float32))
    return out.astype(a.dtype)


def _tree_reduce(stack, n):
    """Binary-tree pairwise adasum over a gathered [n, ...] stack.

    Tree pairing (0,1),(2,3),... per level reproduces the reference's
    halving-doubling combination order.  Non-power-of-two remainders are
    folded in at each level, as the reference's VHDD remainder step does.
    """
    vals = [stack[i] for i in range(n)]
    while len(vals) > 1:
        nxt = []
        for i in range(0, len(vals) - 1, 2):
            nxt.append(adasum_combine(vals[i], vals[i + 1]))
        if len(vals) % 2 == 1:
            nxt[-1] = adasum_combine(nxt[-1], vals[-1])
        vals = nxt
    return vals[0]


def adasum_allreduce(x, axis_name="hvd"):
    """Adasum allreduce usable inside shard_map/jit (any world size)."""
    n = compat_axis_size(axis_name)
    g = lax.all_gather(x, axis_name)  # [n, ...]
    return _tree_reduce(g, n)


def torus_bit_order(n: int, dims) -> "list | None":
    """Rank-bit schedule for halving-doubling rounds that keeps each
    round's exchange on ONE physical ICI torus axis, innermost
    (fastest-varying) axis first.

    ``ordered_devices`` sorts by coordinate tuple lexicographically, so the
    LAST torus dimension varies fastest and owns the LOW rank bits — the
    torus-aligned schedule is therefore the identity bit order; this helper
    *validates* that the decomposition actually holds (all axis extents
    powers of two, multiplying out to ``n``, allowing an extra
    cores-per-chip power-of-two factor as the innermost level) and returns
    None when it doesn't, so callers fall back without assuming a torus.
    """
    if dims is None or n <= 0 or n & (n - 1):
        return None
    prod = 1
    for d in dims:
        if d & (d - 1):
            return None
        prod *= d
    if prod != n:
        # Multi-core chips (e.g. 2 cores/chip) add an innermost factor.
        if prod == 0 or n % prod or (n // prod) & (n // prod - 1):
            return None
    return list(range(n.bit_length() - 1))


def adasum_allreduce_hd(x, axis_name="hvd", bit_order=None, eps=1e-30):
    """Vector-halving-doubling Adasum via ppermute (power-of-two worlds).

    The reference algorithm (``adasum_mpi.cc`` FusedPairwiseReduceWithComm)
    with MPI_Sendrecv replaced by ``lax.ppermute`` over ICI neighbor links:

    - **Halving** round for bit ``b``: partner = rank XOR 2^b.  Each rank
      sends the half of its working segment the partner owns and keeps the
      other.  The Adasum coefficients need dot products over the FULL
      vectors being combined, which at round ``i`` are spread across the
      2^(i+1) ranks of the active XOR subgroup — so each rank computes
      partial (a·b, |a|², |b|²) on its piece and the 3-float partials are
      summed over the subgroup by recursive doubling (exactly how the
      reference distributes the dot products).  Numerics therefore match
      the gather-based ``adasum_allreduce`` tree rank-for-rank, while wire
      cost stays the halving-doubling optimum: each rank moves ~2·|x|
      bytes total instead of the gather's n·|x|.
    - **Doubling** rounds mirror in reverse: partners exchange their
      combined segments and concatenate low/high by the round's rank bit —
      no all-gather anywhere; the whole program is collective-permutes.

    ``bit_order`` (from :func:`torus_bit_order`) schedules which rank bit
    each round exchanges over, so rounds walk physical torus axes
    innermost-first; default is the identity order.
    """
    n = compat_axis_size(axis_name)
    # Static world size: shard_map gives a concrete int at trace time.
    n_static = int(n) if not isinstance(n, int) else n
    if n_static & (n_static - 1):
        raise ValueError("adasum_allreduce_hd requires power-of-two world "
                         "size; use adasum_allreduce instead")
    if n_static == 1:
        return x
    rounds = n_static.bit_length() - 1
    bits = list(bit_order) if bit_order is not None else list(range(rounds))
    assert sorted(bits) == list(range(rounds)), bits
    return _vhd(x, [(axis_name, n_static, b) for b in bits], eps)


def adasum_allreduce_hier(x, cross_axis: str = "cross",
                          local_axis: str = "local",
                          local_bits=None, cross_bits=None, eps=1e-30):
    """Two-level vector-halving-doubling Adasum over a (cross, local) mesh.

    VHD mapped onto the torus axes at BOTH levels (ISSUE 17): the halving
    rounds walk the local (ICI) axis first — by the time a round crosses
    DCN, each rank's working segment has already shrunk to 1/local_size —
    then the cross rounds halve over the leader ring, and doubling mirrors
    back out.  Because ranks are slice-major (local = low rank bits), the
    (local rounds, then cross rounds) schedule combines gradients in the
    SAME binary-tree order as the flat identity-bit-order VHD over the
    whole world, so hierarchical Adasum is the flat algorithm with its
    cheap rounds pinned to ICI and only the halved shards touching DCN.

    ``local_bits``/``cross_bits`` (from
    :func:`horovod_tpu.parallel.topology.hier_bit_orders`, refined by the
    slice's physical torus dims) schedule which rank bit each level's
    rounds exchange over; identity order by default.  Both extents must be
    powers of two — callers gate on :func:`hier_bit_orders` returning
    non-None and keep the flat path otherwise."""
    n_local = int(compat_axis_size(local_axis))
    n_cross = int(compat_axis_size(cross_axis))
    for name, n in (("local", n_local), ("cross", n_cross)):
        if n & (n - 1):
            raise ValueError(
                f"adasum_allreduce_hier requires power-of-two {name} "
                f"extent, got {n}")
    lb = list(local_bits) if local_bits is not None \
        else list(range(n_local.bit_length() - 1))
    cb = list(cross_bits) if cross_bits is not None \
        else list(range(n_cross.bit_length() - 1))
    rounds = [(local_axis, n_local, b) for b in lb] \
        + [(cross_axis, n_cross, b) for b in cb]
    return _vhd(x, rounds, eps)


def _vhd(x, rounds, eps=1e-30):
    """Shared halving-doubling core over a round schedule.

    ``rounds`` is a list of ``(axis_name, axis_size, bit)`` — each halving
    round pairs ranks differing in that bit OF THAT MESH AXIS (a ppermute
    on one axis permutes within every line of the other axes, so XOR
    subgroups compose across axes exactly as rank bits do on a flat
    world).  The Adasum coefficients need dot products over the FULL
    vectors being combined, which at round ``i`` are spread across the
    2^(i+1) ranks of the active subgroup — each rank computes partial
    (a·b, |a|², |b|²) on its piece and the 3-float partials are summed
    over the subgroup by recursive doubling across the same (axis, bit)
    pairs (exactly how the reference distributes the dot products).
    Doubling rounds mirror in reverse: partners exchange their combined
    segments and concatenate low/high by the round's rank bit — no
    all-gather anywhere; the whole program is collective-permutes."""
    if not rounds:
        return x
    total = 1 << len(rounds)
    orig_shape, orig_dtype = x.shape, x.dtype
    flat = x.astype(jnp.float32).reshape(-1)
    pad = (-flat.shape[0]) % total
    flat = jnp.pad(flat, (0, pad))

    def _pair_perm(n_ax, dist):
        return [(i, i ^ dist) for i in range(n_ax)]

    # Halving phase.
    seg = flat  # this rank's current working segment
    for i, (ax, n_ax, b) in enumerate(rounds):
        perm = _pair_perm(n_ax, 1 << b)
        half = seg.shape[0] // 2
        low, high = seg[:half], seg[half:]
        bit = (lax.axis_index(ax) >> b) & 1  # 0 → keep low/send high
        is_low = (bit == 0)
        to_send = jnp.where(is_low, high, low)
        received = lax.ppermute(to_send, ax, perm=perm)
        kept = jnp.where(is_low, low, high)
        # Canonical orientation: "a" is the bit==0 group's vector.  For
        # bit==0 ranks kept is a's piece; for bit==1 ranks received is.
        kr = kept @ received
        kk = kept @ kept
        rr = received @ received
        partials = jnp.stack([kr,
                              jnp.where(is_low, kk, rr),
                              jnp.where(is_low, rr, kk)])
        # Sum partial dots over the active 2^(i+1)-rank subgroup.
        for ax2, n2, b2 in rounds[:i + 1]:
            partials = partials + lax.ppermute(
                partials, ax2, perm=_pair_perm(n2, 1 << b2))
        ab, aa, bb = partials[0], partials[1], partials[2]
        ca = 1.0 - ab / (2.0 * aa + eps)
        cb = 1.0 - ab / (2.0 * bb + eps)
        seg = (jnp.where(is_low, ca, cb) * kept
               + jnp.where(is_low, cb, ca) * received)

    # Doubling phase: reverse rounds; partners swap combined segments and
    # concatenate in rank-bit order.
    for ax, n_ax, b in reversed(rounds):
        perm = _pair_perm(n_ax, 1 << b)
        received = lax.ppermute(seg, ax, perm=perm)
        seg = lax.cond(((lax.axis_index(ax) >> b) & 1) == 0,
                       lambda s, r: jnp.concatenate([s, r]),
                       lambda s, r: jnp.concatenate([r, s]),
                       seg, received)

    full = seg[:-pad] if pad else seg
    return full.reshape(orig_shape).astype(orig_dtype)
