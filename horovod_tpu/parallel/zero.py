"""ZeRO-style sharded optimizer built on reducescatter/allgather.

The reference ships the enabling primitive (``hvd.reducescatter``, v0.28 —
SURVEY.md §2c: "also enables ZeRO-style sharded optimizers") but not the
optimizer itself; this is the TPU-native realization.  Optimizer state is
sharded 1/world across the ``dp`` axis (ZeRO stage 1 + gradient sharding of
stage 2):

    grads --reducescatter(dp)--> local 1/n grad shard
          --inner optimizer on the shard (state lives only for the shard)
          --allgather(dp)--> full updates

Wire cost per step equals plain allreduce (RS + AG), while optimizer-state
memory drops by ``dp``.  Use inside shard_map over the dp axis — or, for
the eager multi-process path, through
``hvd.DistributedOptimizer(..., sharded=True)`` which routes the same
pad+slice convention through the collective engine's reduce-scatter /
allgather pipeline (``jax/optimizer.py``).

**The pad+slice convention** (shared by every sharded consumer — this
module, the eager sharded optimizer, and the state plane's byte sharding in
``elastic/stateplane.py``): a leaf of ``n`` elements is flattened, padded
with zeros to the next multiple of ``world`` and sliced into ``world``
even shards of ``(n + pad) // world`` elements; rank ``r`` owns elements
``[r*per, (r+1)*per)`` of the padded buffer.  ``shard_info`` is the one
pure function every rank derives identical boundaries from.
"""

from __future__ import annotations

from typing import Any, NamedTuple, Tuple

import jax
import jax.numpy as jnp
import optax
from jax import lax
from ..compat import axis_size as compat_axis_size


def shard_info(n: int, world: int) -> Tuple[int, int]:
    """``(pad, per)`` of the pad+slice convention: a flattened leaf of
    ``n`` elements pads with ``pad`` zeros and splits into ``world`` even
    shards of ``per`` elements.  Pure math (no jax) — rank-invariant by
    construction, and the same convention ``elastic/stateplane.py``
    applies to checkpoint bytes (``shard_bounds``)."""
    world = max(1, int(world))
    n = int(n)
    pad = (-n) % world
    return pad, (n + pad) // world


def shard_slice_host(arr, rank: int, world: int):
    """Rank ``rank``'s 1/world shard of a host array under the pad+slice
    convention (numpy, flattened).  The host-side twin of
    :func:`_shard_leaf` — the eager sharded optimizer slices its initial
    state with it, and the elastic restore path re-slices a recovered
    full optimizer state into the joining rank's shard."""
    import numpy as np
    flat = np.asarray(arr).reshape(-1)
    pad, per = shard_info(flat.shape[0], world)
    if pad:
        flat = np.concatenate([flat, np.zeros((pad,), flat.dtype)])
    return flat[rank * per:(rank + 1) * per]


def unshard_host(shards, n: int, shape, dtype=None):
    """Reassemble a leaf from its per-rank host shards (inverse of
    :func:`shard_slice_host`): concatenate, drop the pad, reshape."""
    import numpy as np
    flat = np.concatenate([np.asarray(s).reshape(-1) for s in shards])[:n]
    out = flat.reshape(shape)
    return out.astype(dtype) if dtype is not None else out


class _ZeroState(NamedTuple):
    inner_state: Any
    leaf_pads: Any          # static per-leaf padding metadata


def _shard_leaf(g, axis_name):
    n = compat_axis_size(axis_name)
    flat = g.reshape(-1)
    if flat.shape[0] == 0:
        # Empty leaf: every rank's shard is the empty array — running a
        # zero-length psum_scatter would be pointless (and some backends
        # reject it outright).
        return flat, 0
    pad, _per = shard_info(flat.shape[0], n)
    if pad:
        flat = jnp.pad(flat, (0, pad))
    if n == 1:
        # world of 1: the shard IS the whole (padded) leaf; psum_scatter
        # over a 1-sized axis is the identity, skip the collective.
        return flat, pad
    return lax.psum_scatter(flat, axis_name, tiled=True), pad


def _slice_leaf(p, axis_name):
    """This rank's 1/world slice of a REPLICATED leaf — pad+slice via
    ``axis_index``, NO reduction.  The in-graph twin of the eager path's
    ``_device_shard``.  Params must come through here, never
    :func:`_shard_leaf`: psum_scatter of a replicated leaf returns the
    slice of the SUM over ranks (world × the value), which would hand
    param-dependent inner transforms (adamw weight decay) world-scaled
    parameters."""
    n = compat_axis_size(axis_name)
    flat = p.reshape(-1)
    if flat.shape[0] == 0:
        return flat
    pad, per = shard_info(flat.shape[0], n)
    if pad:
        flat = jnp.pad(flat, (0, pad))
    if n == 1:
        return flat
    r = lax.axis_index(axis_name)
    return lax.dynamic_slice_in_dim(flat, r * per, per)


def _unshard_leaf(u, pad, shape, axis_name):
    n = compat_axis_size(axis_name)
    if u.shape[0] == 0:
        return jnp.zeros(shape, u.dtype) if 0 not in shape else \
            u.reshape(shape)
    full = lax.all_gather(u, axis_name, tiled=True) if n > 1 else u
    if pad:
        full = full[:-pad]
    return full.reshape(shape)


def state_specs(opt_state, axis_name: str = "dp"):
    """``PartitionSpec`` tree for a sharded-optimizer state: every array
    leaf is a distinct 1/world shard over ``axis_name`` (flattened, dim
    0); scalar leaves (step counters) are replicated.  Feed this as the
    ``opt_state_specs`` of ``parallel/spmd.make_sharded_train_step``."""
    from jax.sharding import PartitionSpec as P

    def spec(leaf):
        return P(axis_name) if getattr(leaf, "ndim", 0) >= 1 else P()

    return jax.tree_util.tree_map(spec, opt_state)


def init_sharded_state(inner: optax.GradientTransformation, params,
                       mesh, axis_name: str = "dp"):
    """Initialize a sharded optimizer state ON the mesh: returns
    ``(opt_state, opt_state_specs)`` where every array leaf is the global
    ``[world * per]`` array sharded ``P(axis_name)`` — 1/world per device
    in HBM, ready to feed a ``make_sharded_train_step`` whose step uses
    :func:`sharded_optimizer`.

    Two passes: the state *structure* comes from an abstract
    ``eval_shape`` over host-computed shard shapes (the pad+slice
    convention is pure math, so no device executes anything), which
    yields the spec tree; the real init then runs under ``shard_map``
    with those out_specs.
    """
    from ..compat import shard_map
    from jax.sharding import PartitionSpec as P

    world = mesh.shape[axis_name]
    opt = sharded_optimizer(inner, axis_name=axis_name)

    # Pass 1: structure/specs from abstract shard shapes.
    def shard_struct(p):
        _pad, per = shard_info(int(p.size), world)
        return jax.ShapeDtypeStruct((per,), p.dtype)

    shard_shapes = jax.tree_util.tree_map(shard_struct, params)
    abstract = jax.eval_shape(
        lambda ps: _ZeroState(inner.init(ps), ()), shard_shapes)
    specs = state_specs(abstract, axis_name)

    # Pass 2: the real init under shard_map (each device slices its own
    # shard of the padded flat leaves — no reduction).
    init = shard_map(opt.init, mesh=mesh, in_specs=(P(),),
                     out_specs=specs, check_vma=False)
    return jax.jit(init)(params), specs


def sharded_optimizer(inner: optax.GradientTransformation,
                      axis_name: str = "dp",
                      average: bool = True) -> optax.GradientTransformation:
    """Wrap an optax optimizer so its state is sharded over ``axis_name``.

    Per-shard semantics caveat (documented ZeRO behavior): the inner
    transformation sees only this rank's 1/world shard of each leaf, so
    elementwise optimizers (sgd/adam/adamw/...) are exact, while
    transforms that aggregate across the whole tree (global-norm
    clipping) aggregate per shard instead — compose those *outside* the
    sharded wrapper if global semantics are required.
    """

    def init_fn(params):
        sharded_params = jax.tree_util.tree_map(
            lambda p: _slice_leaf(p, axis_name), params)
        return _ZeroState(inner.init(sharded_params), ())

    def update_fn(grads, state: _ZeroState, params=None):
        n = compat_axis_size(axis_name)
        leaves, treedef = jax.tree_util.tree_flatten(grads)
        shapes = [g.shape for g in leaves]
        shard_pairs = [_shard_leaf(g, axis_name) for g in leaves]
        g_shards = [s for s, _ in shard_pairs]
        pads = [p for _, p in shard_pairs]
        if average:
            # Same AVERAGE semantics as the allreduce path: true division
            # for floats, floor division for ints.
            g_shards = [g / jnp.asarray(n, g.dtype)
                        if jnp.issubdtype(g.dtype, jnp.floating) else g // n
                        for g in g_shards]
        g_shards = jax.tree_util.tree_unflatten(treedef, g_shards)
        p_shards = None
        if params is not None:
            p_leaves = jax.tree_util.tree_flatten(params)[0]
            p_shards = jax.tree_util.tree_unflatten(
                treedef, [_slice_leaf(p, axis_name) for p in p_leaves])
        u_shards, inner_state = inner.update(g_shards, state.inner_state,
                                             p_shards)
        u_leaves = jax.tree_util.tree_flatten(u_shards)[0]
        updates = jax.tree_util.tree_unflatten(
            treedef, [_unshard_leaf(u, pad, shape, axis_name)
                      for u, pad, shape in zip(u_leaves, pads, shapes)])
        return updates, _ZeroState(inner_state, ())

    return optax.GradientTransformation(init_fn, update_fn)


# --------------------------------------------------------------------- FSDP
# Full parameter sharding (ISSUE 18, ZeRO stage 3): the resident truth is
# the 1/world PARAMETER shard, not just optimizer state.  Forward/backward
# materialize full parameters with gather_full_params (an allgather the
# eager pipeline prefetch-overlaps); backward's gradients reduce-scatter
# straight into the owning shard; the inner optax update runs shard-local.
# Wire per step: AG(params) + RS(grads) = the same 2·B·(world-1)/world ring
# bytes as the stage-1 sharded path's RS + delta-AG — model memory drops
# to shard + the bounded prefetch window at unchanged wire cost.

class _FullZeroState(NamedTuple):
    inner_state: Any        # inner optax state over the [per] shards
    param_shards: Any       # tree of flat [per] leaves — the RESIDENT params


def full_sharded_optimizer(inner: optax.GradientTransformation,
                           axis_name: str = "dp",
                           average: bool = True
                           ) -> optax.GradientTransformation:
    """ZeRO-3 wrapper: parameters live ONLY as the state's 1/world shards.

    ``init(params)`` slices the full (replicated) parameters into this
    rank's shards; ``update(grads, state)`` reduce-scatters the gradients,
    advances the resident shards through the inner optimizer, and returns
    the allgathered full *updates* so plain ``optax.apply_updates``
    callers still work — a caller that instead keeps only the shard state
    and rematerializes via :func:`gather_full_params` lets XLA dead-code-
    eliminate that delta-allgather, so either usage costs the same
    RS + one-AG wire per step.  The ``params`` argument of ``update`` is
    ignored: the resident shards are the authoritative parameters (a
    replicated copy need never exist).

    Same per-shard semantics caveat as :func:`sharded_optimizer`:
    elementwise inner transforms are exact; whole-tree aggregations
    (global-norm clipping) act per shard."""

    def init_fn(params):
        shards = jax.tree_util.tree_map(
            lambda p: _slice_leaf(p, axis_name), params)
        return _FullZeroState(inner.init(shards), shards)

    def update_fn(grads, state: _FullZeroState, params=None):
        del params                       # resident shards are the truth
        n = compat_axis_size(axis_name)
        leaves, treedef = jax.tree_util.tree_flatten(grads)
        shapes = [g.shape for g in leaves]
        shard_pairs = [_shard_leaf(g, axis_name) for g in leaves]
        g_shards = [s for s, _ in shard_pairs]
        pads = [p for _, p in shard_pairs]
        if average:
            g_shards = [g / jnp.asarray(n, g.dtype)
                        if jnp.issubdtype(g.dtype, jnp.floating) else g // n
                        for g in g_shards]
        g_shards = jax.tree_util.tree_unflatten(treedef, g_shards)
        u_shards, inner_state = inner.update(
            g_shards, state.inner_state, state.param_shards)
        new_shards = optax.apply_updates(state.param_shards, u_shards)
        u_leaves = jax.tree_util.tree_flatten(u_shards)[0]
        updates = jax.tree_util.tree_unflatten(
            treedef, [_unshard_leaf(u, pad, shape, axis_name)
                      for u, pad, shape in zip(u_leaves, pads, shapes)])
        return updates, _FullZeroState(inner_state, new_shards)

    return optax.GradientTransformation(init_fn, update_fn)


def gather_full_params(state: _FullZeroState, template,
                       axis_name: str = "dp"):
    """Rematerialize the full parameter tree from the resident shards —
    the in-graph FSDP prefetch allgather.  ``template`` supplies each
    leaf's full shape/dtype (the original params tree or its
    ``ShapeDtypeStruct``s); pad widths re-derive from ``shard_info``, so
    no metadata travels in the state."""
    n = compat_axis_size(axis_name)

    def gather(t, shard):
        shape = tuple(t.shape)
        size = 1
        for d in shape:
            size *= int(d)
        pad, _per = shard_info(size, n)
        return _unshard_leaf(shard, pad, shape, axis_name)

    return jax.tree_util.tree_map(gather, template, state.param_shards)


def init_full_sharded_state(inner: optax.GradientTransformation, params,
                            mesh, axis_name: str = "dp"):
    """Initialize a full-sharded (ZeRO-3) state ON the mesh: returns
    ``(state, state_specs)`` where every array leaf — inner optimizer
    state AND the resident ``param_shards`` — is the global
    ``[world * per]`` array sharded ``P(axis_name)``.  The two-pass
    structure mirrors :func:`init_sharded_state`."""
    from ..compat import shard_map
    from jax.sharding import PartitionSpec as P

    world = mesh.shape[axis_name]
    opt = full_sharded_optimizer(inner, axis_name=axis_name)

    def shard_struct(p):
        _pad, per = shard_info(int(p.size), world)
        return jax.ShapeDtypeStruct((per,), p.dtype)

    shard_shapes = jax.tree_util.tree_map(shard_struct, params)
    abstract = jax.eval_shape(
        lambda ps: _FullZeroState(inner.init(ps), ps), shard_shapes)
    specs = state_specs(abstract, axis_name)

    init = shard_map(opt.init, mesh=mesh, in_specs=(P(),),
                     out_specs=specs, check_vma=False)
    return jax.jit(init)(params), specs
