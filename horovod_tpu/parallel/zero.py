"""ZeRO-style sharded optimizer built on reducescatter/allgather.

The reference ships the enabling primitive (``hvd.reducescatter``, v0.28 —
SURVEY.md §2c: "also enables ZeRO-style sharded optimizers") but not the
optimizer itself; this is the TPU-native realization.  Optimizer state is
sharded 1/world across the ``dp`` axis (ZeRO stage 1 + gradient sharding of
stage 2):

    grads --reducescatter(dp)--> local 1/n grad shard
          --inner optimizer on the shard (state lives only for the shard)
          --allgather(dp)--> full updates

Wire cost per step equals plain allreduce (RS + AG), while optimizer-state
memory drops by ``dp``.  Use inside shard_map over the dp axis.
"""

from __future__ import annotations

from typing import Any, NamedTuple

import jax
import jax.numpy as jnp
import optax
from jax import lax
from ..compat import axis_size as compat_axis_size


class _ZeroState(NamedTuple):
    inner_state: Any
    leaf_pads: Any          # static per-leaf padding metadata


def _shard_leaf(g, axis_name):
    n = compat_axis_size(axis_name)
    flat = g.reshape(-1)
    pad = (-flat.shape[0]) % n
    if pad:
        flat = jnp.pad(flat, (0, pad))
    return lax.psum_scatter(flat, axis_name, tiled=True), pad


def _unshard_leaf(u, pad, shape, axis_name):
    full = lax.all_gather(u, axis_name, tiled=True)
    if pad:
        full = full[:-pad]
    return full.reshape(shape)


def sharded_optimizer(inner: optax.GradientTransformation,
                      axis_name: str = "dp",
                      average: bool = True) -> optax.GradientTransformation:
    """Wrap an optax optimizer so its state is sharded over ``axis_name``."""

    def init_fn(params):
        def shard_param(p):
            s, _ = _shard_leaf(p, axis_name)
            return s
        sharded_params = jax.tree_util.tree_map(shard_param, params)
        return _ZeroState(inner.init(sharded_params), ())

    def update_fn(grads, state: _ZeroState, params=None):
        n = compat_axis_size(axis_name)
        leaves, treedef = jax.tree_util.tree_flatten(grads)
        shapes = [g.shape for g in leaves]
        shard_pairs = [_shard_leaf(g, axis_name) for g in leaves]
        g_shards = [s for s, _ in shard_pairs]
        pads = [p for _, p in shard_pairs]
        if average:
            g_shards = [g / jnp.asarray(n, g.dtype) for g in g_shards]
        g_shards = jax.tree_util.tree_unflatten(treedef, g_shards)
        p_shards = None
        if params is not None:
            p_leaves = jax.tree_util.tree_flatten(params)[0]
            p_shards = jax.tree_util.tree_unflatten(
                treedef, [_shard_leaf(p, axis_name)[0] for p in p_leaves])
        u_shards, inner_state = inner.update(g_shards, state.inner_state,
                                             p_shards)
        u_leaves = jax.tree_util.tree_flatten(u_shards)[0]
        updates = jax.tree_util.tree_unflatten(
            treedef, [_unshard_leaf(u, pad, shape, axis_name)
                      for u, pad, shape in zip(u_leaves, pads, shapes)])
        return updates, _ZeroState(inner_state, ())

    return optax.GradientTransformation(init_fn, update_fn)
