"""Pipeline parallelism: GPipe-style microbatched stages over ``ppermute``.

No reference analogue (SURVEY.md §2c: pipeline parallelism is ABSENT in
Horovod) — this is a beyond-parity capability built TPU-first, the same way
ring/Ulysses sequence parallelism were: the ``pp`` mesh axis holds one
pipeline stage per device group, activations hop stage→stage over ICI with
``lax.ppermute``, and the whole schedule is one ``lax.scan`` inside
``shard_map`` — a single compiled program, no host round-trips between
ticks.

Schedule: classic GPipe fill/steady/drain.  With ``S`` stages and ``M``
microbatches the scan runs ``S + M - 1`` ticks; at tick ``t`` stage ``s``
processes microbatch ``m = t - s`` (when ``0 <= m < M``).  Bubble fraction
``(S-1)/(S+M-1)`` — pick ``M >> S``.  The stage function must be
shape-preserving (transformer blocks are), which is what lets one carry
buffer serve every stage.

Differentiable end to end: ``ppermute`` and ``scan`` have transposes, so
``jax.grad`` of a loss on the last stage's outputs produces correct
per-stage parameter gradients (the backward pipeline runs in the scan's
transpose, reverse order — 1F1B-style interleaving is future work).

Use inside ``shard_map`` with stage params sharded over ``pp``:

    out = pipeline_apply(block_fn, stage_params, micro_x, axis_name="pp")
"""

from __future__ import annotations

from typing import Callable

import jax
import jax.numpy as jnp
from jax import lax
from ..compat import axis_size as compat_axis_size


def stage_index(axis_name: str = "pp"):
    return lax.axis_index(axis_name)


def pipeline_apply(fn: Callable, stage_params, micro_x,
                   axis_name: str = "pp",
                   broadcast_out: bool = False,
                   remat: bool = False,
                   with_aux: bool = False,
                   aux_init=None):
    """Run microbatches through the stage pipeline.

    fn: ``(stage_params, x[mb, ...]) -> y[mb, ...]`` (shape-preserving);
    this rank applies ITS stage's params (already sharded over
    ``axis_name`` by the enclosing shard_map).
    micro_x: ``[M, mb, ...]`` microbatched input (consumed by stage 0).
    Returns ``[M, mb, ...]`` outputs — valid on the LAST stage (zeros
    elsewhere) unless ``broadcast_out``, which broadcasts them to every
    stage with one psum (exact because every non-last stage holds zeros;
    a schedule that leaves real data on other stages must not reuse it).

    ``remat=True`` wraps the stage in ``jax.checkpoint``: the backward
    scan recomputes each tick's stage forward from its carry instead of
    storing every tick's intermediates — activation memory drops from
    O(ticks · stage_depth) to O(ticks) carries + one stage recompute.
    This is the memory dividend 1F1B buys on imperative runtimes; under
    XLA's scan transpose (which already interleaves each tick's backward
    with its recompute, 1F1B-style) remat is the idiomatic lever, so a
    literal hand-scheduled 1F1B variant is deliberately not implemented.

    ``with_aux=True``: ``fn`` returns ``(y, aux)`` and the call returns
    ``(outs, aux_total)`` where aux_total accumulates every VALID
    (non-bubble) tick's aux on THIS stage — a per-stage partial (each
    stage saw only its own layers); callers sum across pp with a psum,
    exactly like the MoE router-balance loss wants.  ``aux`` is a scalar
    by default; pass ``aux_init`` (e.g. ``jnp.zeros((2,))``) when the
    stage emits a vector of accumulators — scan demands a shape-stable
    carry, so the init must match fn's aux shape.
    """
    if remat:
        fn = jax.checkpoint(fn)
    n = compat_axis_size(axis_name)
    idx = lax.axis_index(axis_name)
    m_total = micro_x.shape[0]
    ticks = m_total + n - 1
    # stage s -> s+1 (the last stage's send wraps to 0 and is ignored —
    # stage 0 reads micro_x, never the carry).
    perm = [(i, (i + 1) % n) for i in range(n)]

    def tick(carry, t):
        buf, outs, aux_acc = carry
        x0 = micro_x[jnp.clip(t, 0, m_total - 1)]
        x_in = jnp.where(idx == 0, x0, buf)
        if with_aux:
            y, aux = fn(stage_params, x_in)
        else:
            y = fn(stage_params, x_in)
            aux = 0.0
        m = t - idx                      # microbatch this stage holds now
        valid = jnp.logical_and(m >= 0, m < m_total)
        # Bubble ticks compute garbage; zero it so it can't poison the
        # carry (NaN from fn(params, junk) would otherwise propagate).
        y = jnp.where(valid, y, jnp.zeros_like(y))
        aux_acc = aux_acc + jnp.where(valid, aux, 0.0)
        outs = lax.cond(
            jnp.logical_and(valid, idx == n - 1),
            lambda o: lax.dynamic_update_index_in_dim(
                o, y, jnp.clip(m, 0, m_total - 1), 0),
            lambda o: o, outs)
        buf = lax.ppermute(y, axis_name, perm)
        return (buf, outs, aux_acc), None

    buf0 = jnp.zeros_like(micro_x[0])
    outs0 = jnp.zeros_like(micro_x)
    aux0 = jnp.zeros((), jnp.float32) if aux_init is None else aux_init
    (buf, outs, aux_total), _ = lax.scan(
        tick, (buf0, outs0, aux0), jnp.arange(ticks))

    if broadcast_out:
        # Every stage but the last holds zeros, so a psum over the pp axis
        # IS the broadcast of the last stage's outputs.
        outs = lax.psum(outs, axis_name)
    if with_aux:
        return outs, aux_total
    return outs


def microbatch(x, n_micro: int):
    """[B, ...] -> [n_micro, B // n_micro, ...] (B must divide evenly)."""
    b = x.shape[0]
    if b % n_micro:
        raise ValueError(f"batch {b} not divisible into {n_micro} "
                         f"microbatches")
    return x.reshape((n_micro, b // n_micro) + x.shape[1:])
