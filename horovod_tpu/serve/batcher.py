"""Continuous-batching admission queue for the serving plane (no jax).

The front half of the data-parallel serving plane (ISSUE 19,
``docs/serving.md``): requests arrive one at a time (HTTP or in-process),
the replica's forward loop consumes them in *padded-bucket* batches, and
the two sides meet here.  Three ideas carried over from the training
engine rather than invented fresh:

- **Bounded in-flight window** — ``max_inflight`` is the serving twin of
  ``HOROVOD_MAX_INFLIGHT``'s :class:`~..ops.scheduler.InflightRing`
  semantics: at most N batches may be dispatched-but-unsettled at once,
  and :meth:`next_batch` blocks while the window is full.  Same reason as
  training: unbounded dispatch converts a slow device into unbounded
  host-memory growth and tail-latency collapse.
- **Padded buckets** — batches are padded up to a fixed menu of sizes
  (default: powers of two up to ``max_batch``) so the replica sees a
  handful of distinct batch shapes, each compiled once and keyed into the
  :class:`~..ops.scheduler.FusedProgramCache`.  Batch-size churn between
  requests never recompiles.
- **Backpressure, not buffering** — :meth:`submit` raises
  :class:`QueueFull` the moment the ingest queue hits ``queue_depth``;
  the front door turns that into HTTP 429 plus a queue-depth signal the
  autoscaler reads.  An admission queue that silently grows just moves
  the overload from the caller's timeout to the tail of the queue.

Everything here is stdlib-only and clock-injected (``clock=`` in the
constructor) so the jax-free test tier drives admission, deadlines,
bucketing and backpressure deterministically.
"""

from __future__ import annotations

import itertools
import os
import threading
import time
from collections import OrderedDict
from typing import Callable, List, Optional, Sequence, Tuple

from ..utils.logging import get_logger

log = get_logger()

# Latency histogram buckets in MILLISECONDS (request-scale, not the
# registry's coordinator-cycle-microsecond defaults).
LATENCY_MS_BUCKETS = (1.0, 2.5, 5.0, 10.0, 25.0, 50.0, 100.0, 250.0,
                      500.0, 1000.0, 2500.0, 10000.0)


class QueueFull(RuntimeError):
    """Admission refused: the ingest queue is at ``queue_depth``.  The
    front door maps this to HTTP 429."""


class Draining(RuntimeError):
    """Admission refused: the replica is draining (cordoned by the
    elastic driver).  The front door maps this to HTTP 503."""


class DeadlineExceeded(RuntimeError):
    """The request expired before a replica picked it up (or the caller
    stopped waiting).  The front door maps this to HTTP 504."""


class Retryable(RuntimeError):
    """Base for failures the front door may transparently retry: the
    request itself is fine, the attempt died underneath it.  Retries are
    deadline-bounded — backoff is charged against the request's original
    deadline, never extended past it."""


class ReplicaFaulted(Retryable):
    """The replica lost a peer mid-batch (HVD303 / clean LEAVE race).
    The batch's requests are failed with this so the front door can
    re-submit them once the surviving world re-rendezvouses.  Maps to
    HTTP 503 + ``Retry-After`` when retries are exhausted."""


class ForwardFailed(Retryable):
    """One forward execution failed (injected I/O fault, transient device
    error).  Retryable until quarantine decides the request itself is the
    problem.  Maps to HTTP 500 when retries are exhausted."""


class RequestQuarantined(RuntimeError):
    """Terminal: this request failed ``quarantine_after`` consecutive
    forwards — the input is treated as poisoned and is never re-batched
    (one bad request must not wedge the replica).  Maps to HTTP 500."""


class Cancelled(RuntimeError):
    """The request was cancelled before dispatch (a hedge whose twin
    finished first).  Never surfaces to HTTP: the winner's response is
    the terminal one."""


def parse_buckets(spec: str, max_batch: int) -> Tuple[int, ...]:
    """Bucket menu from ``HOROVOD_SERVE_BUCKETS`` (comma-separated sizes);
    empty spec → powers of two up to ``max_batch``.  Always sorted, always
    capped by ``max_batch``, always non-empty."""
    max_batch = max(1, int(max_batch))
    sizes: List[int] = []
    if spec:
        for tok in spec.split(","):
            tok = tok.strip()
            if tok:
                sizes.append(int(tok))
        sizes = [s for s in sizes if 1 <= s <= max_batch]
    if not sizes:
        sizes = list(itertools.takewhile(lambda s: s <= max_batch,
                                         (1 << i for i in range(31))))
    if max_batch not in sizes:
        sizes.append(max_batch)
    return tuple(sorted(set(sizes)))


class Request:
    """One in-flight inference request; ``wait()`` is the caller's side."""

    __slots__ = ("id", "key", "inputs", "deadline", "enqueued_at", "_event",
                 "result", "error", "completed_at", "_callbacks", "_cb_lock")
    _ids = itertools.count()

    def __init__(self, inputs, deadline: float, enqueued_at: float,
                 key: Optional[str] = None):
        self.id = next(Request._ids)
        self.key = key if key is not None else f"req-{self.id}"
        self.inputs = inputs
        self.deadline = deadline
        self.enqueued_at = enqueued_at
        self._event = threading.Event()
        self.result = None
        self.error: Optional[BaseException] = None
        self.completed_at: Optional[float] = None
        self._callbacks: List[Callable[["Request"], None]] = []
        self._cb_lock = threading.Lock()

    def done(self) -> bool:
        return self._event.is_set()

    def on_done(self, cb: Callable[["Request"], None]) -> None:
        """Register ``cb(request)`` to run when this request settles;
        fires immediately if it already has (the hedging race is between
        registration and settlement)."""
        with self._cb_lock:
            if not self._event.is_set():
                self._callbacks.append(cb)
                return
        cb(self)

    def _fire_settled(self) -> List[Callable[["Request"], None]]:
        """Flip the settled event and drain the callback list atomically;
        the batcher invokes the returned callbacks outside its own lock."""
        with self._cb_lock:
            self._event.set()
            cbs, self._callbacks = self._callbacks, []
        return cbs

    def wait(self, timeout: Optional[float] = None):
        """Block until the replica settles this request; returns the
        result or raises the routed error (DeadlineExceeded on its own
        timeout)."""
        if not self._event.wait(timeout):
            raise DeadlineExceeded(
                f"request {self.id}: no result within {timeout}s")
        if self.error is not None:
            raise self.error
        return self.result


class Batch:
    """One dispatched unit: up to ``bucket`` requests padded to a fixed
    bucket size.  Results route back by POSITION — ``complete(results)``
    aligns ``results[i]`` with ``requests[i]``; the padding rows past
    ``size`` are the replica's to discard."""

    __slots__ = ("requests", "bucket")

    def __init__(self, requests: List[Request], bucket: int):
        self.requests = requests
        self.bucket = bucket

    @property
    def size(self) -> int:
        return len(self.requests)


class ContinuousBatcher:
    """Admission queue + padded-bucket batch former (thread-safe)."""

    def __init__(self, max_batch: int = 8,
                 buckets: Optional[Sequence[int]] = None,
                 deadline_ms: float = 1000.0, max_inflight: int = 2,
                 queue_depth: int = 128, registry=None,
                 quarantine_after: Optional[int] = None,
                 clock: Callable[[], float] = time.monotonic):
        self.max_batch = max(1, int(max_batch))
        if buckets:
            self.buckets = tuple(sorted({int(b) for b in buckets
                                         if 1 <= int(b) <= self.max_batch}
                                        | {self.max_batch}))
        else:
            self.buckets = parse_buckets("", self.max_batch)
        self.deadline_ms = float(deadline_ms)
        self.max_inflight = max(1, int(max_inflight))
        self.queue_depth = max(1, int(queue_depth))
        self._clock = clock
        if quarantine_after is None:
            try:
                quarantine_after = int(os.environ.get(
                    "HOROVOD_SERVE_QUARANTINE_AFTER", "") or 3)
            except ValueError:
                quarantine_after = 3
        self.quarantine_after = max(1, int(quarantine_after))
        self._cv = threading.Condition()
        self._queue: List[Request] = []
        self._inflight = 0
        self._draining = False
        # Idempotent re-submission: request-id -> live (unsettled) Request.
        # A front-door retry that races its own earlier attempt gets the
        # resident request back instead of double-executing it.
        self._resident: dict = {}
        # Poisoned-request quarantine: request-id -> consecutive forward
        # failures.  Reset on success, terminal at quarantine_after.
        # Ordered by last UPDATE so the size bound evicts stale entries,
        # never the count of a request actively being retried.
        self._fail_counts: OrderedDict = OrderedDict()
        # Telemetry: real registry metrics when the monitor is up, cheap
        # stand-ins otherwise — the batcher never imports jax either way.
        if registry is None:
            from ..monitor.registry import MetricRegistry
            registry = MetricRegistry()
        self.registry = registry
        self._m_requests = registry.counter(
            "hvd_serve_requests_total", "requests admitted")
        self._m_rejected = registry.counter(
            "hvd_serve_rejected_total", "requests refused: queue full")
        self._m_expired = registry.counter(
            "hvd_serve_expired_total", "requests expired before dispatch")
        self._m_batches = registry.counter(
            "hvd_serve_batches_total", "batches dispatched")
        self._m_padding = registry.counter(
            "hvd_serve_padding_rows_total",
            "bucket padding rows dispatched")
        self._m_latency = registry.histogram(
            "hvd_serve_latency_ms", "request latency, admission to result",
            buckets=LATENCY_MS_BUCKETS)
        self._g_queue = registry.gauge(
            "hvd_serve_queue_depth", "requests awaiting dispatch")
        self._g_inflight = registry.gauge(
            "hvd_serve_inflight", "dispatched, unsettled batches")
        self._m_resubmitted = registry.counter(
            "hvd_serve_resubmitted_total",
            "idempotent re-submissions joined to a resident request")
        self._m_quarantined = registry.counter(
            "hvd_serve_quarantined_total",
            "requests failed terminally by the poisoned-request quarantine")
        self._m_replica_faults = registry.counter(
            "hvd_serve_replica_faults_total",
            "batches failed retryably by a replica peer fault")
        self._m_requeued = registry.counter(
            "hvd_serve_requeued_total",
            "queued requests preserved (original deadlines) across a "
            "replica fault")
        self._m_cancelled = registry.counter(
            "hvd_serve_cancelled_total",
            "queued requests cancelled before dispatch (hedge losers)")

    # ----------------------------------------------------------- admission
    def submit(self, inputs, deadline_ms: Optional[float] = None,
               request_id: Optional[str] = None) -> Request:
        """Admit one request or refuse loudly (QueueFull / Draining).

        ``request_id`` makes admission idempotent: a re-submission under
        an id that is still resident (queued or in a dispatched batch)
        returns the EXISTING request instead of double-executing it — the
        front door's retry path leans on this so a retry that races its
        own not-yet-settled attempt joins it rather than forking it."""
        now = self._clock()
        ttl = self.deadline_ms if deadline_ms is None else float(deadline_ms)
        with self._cv:
            if request_id is not None:
                live = self._resident.get(request_id)
                if live is not None and not live.done():
                    self._m_resubmitted.inc()
                    return live
            if self._draining:
                raise Draining("replica is draining; not accepting work")
            if len(self._queue) >= self.queue_depth:
                self._m_rejected.inc()
                raise QueueFull(
                    f"ingest queue at depth {self.queue_depth}")
            req = Request(inputs, deadline=now + ttl / 1000.0,
                          enqueued_at=now, key=request_id)
            self._resident[req.key] = req
            self._queue.append(req)
            self._m_requests.inc()
            self._g_queue.set(len(self._queue))
            self._cv.notify_all()
        return req

    def bucket_for(self, n: int) -> int:
        """Smallest bucket that fits ``n`` requests (clamped to the
        largest — callers never form batches past ``max_batch``)."""
        for b in self.buckets:
            if n <= b:
                return b
        return self.buckets[-1]

    # ------------------------------------------------------------ dispatch
    def next_batch(self, timeout: Optional[float] = None) -> Optional[Batch]:
        """Block until (a) work is queued AND (b) the in-flight window has
        room, then pop up to ``max_batch`` requests as one padded-bucket
        batch.  Expired requests are failed in place (never dispatched).
        None on timeout or when draining with an empty queue."""
        deadline = None if timeout is None else self._clock() + timeout
        with self._cv:
            while True:
                self._expire_locked()
                if self._queue and self._inflight < self.max_inflight:
                    take = min(len(self._queue), self.max_batch)
                    reqs = self._queue[:take]
                    del self._queue[:take]
                    bucket = self.bucket_for(take)
                    self._inflight += 1
                    self._m_batches.inc()
                    self._m_padding.inc(bucket - take)
                    self._g_queue.set(len(self._queue))
                    self._g_inflight.set(self._inflight)
                    return Batch(reqs, bucket)
                if self._draining and not self._queue:
                    return None
                wait = None
                if deadline is not None:
                    wait = deadline - self._clock()
                    if wait <= 0:
                        return None
                self._cv.wait(wait if wait is not None else 0.1)

    def _expire_locked(self) -> None:
        now = self._clock()
        keep: List[Request] = []
        for r in self._queue:
            if r.deadline <= now:
                self._m_expired.inc()
                self._settle(r, error=DeadlineExceeded(
                    f"request {r.id}: expired after "
                    f"{(now - r.enqueued_at) * 1e3:.0f}ms in queue"))
            else:
                keep.append(r)
        if len(keep) != len(self._queue):
            self._queue[:] = keep
            self._g_queue.set(len(keep))

    # ------------------------------------------------------------ settling
    def _settle(self, req: Request, result=None,
                error: Optional[BaseException] = None) -> None:
        req.result = result
        req.error = error
        req.completed_at = self._clock()
        with self._cv:   # RLock: safe under _expire_locked's held _cv
            self._resident.pop(req.key, None)
            if error is None:
                self._fail_counts.pop(req.key, None)
                self._m_latency.observe(
                    (req.completed_at - req.enqueued_at) * 1e3)
        for cb in req._fire_settled():
            cb(req)

    def complete(self, batch: Batch, results: Sequence) -> None:
        """Route ``results`` back by position; frees one window slot."""
        if len(results) < batch.size:
            raise ValueError(
                f"batch of {batch.size} got {len(results)} results")
        for req, res in zip(batch.requests, results):
            self._settle(req, result=res)
        with self._cv:
            self._inflight -= 1
            self._g_inflight.set(self._inflight)
            self._cv.notify_all()

    def fail(self, batch: Batch, error: BaseException) -> None:
        """Fail every request in ``batch`` with a typed error, charging
        the poisoned-request quarantine: each consecutive forward failure
        under the same request id counts toward ``quarantine_after``, at
        which point the request is failed TERMINALLY
        (:class:`RequestQuarantined`) instead of retryably — a re-submitted
        poisoned input cannot wedge the replica into failing every batch
        it rides in."""
        for req in batch.requests:
            with self._cv:
                n = self._fail_counts.get(req.key, 0) + 1
                if n >= self.quarantine_after:
                    self._fail_counts.pop(req.key, None)
                    self._m_quarantined.inc()
                    routed: BaseException = RequestQuarantined(
                        f"request {req.key}: {n} consecutive forward "
                        f"failures (last: {error}); quarantined")
                else:
                    self._fail_counts[req.key] = n
                    self._fail_counts.move_to_end(req.key)
                    # Bound the book-keeping: a failed request that is
                    # never re-submitted must not leak its count forever.
                    # Least-recently-UPDATED goes first, so a request
                    # mid-retry never loses its streak to the bound.
                    while len(self._fail_counts) > 4 * self.queue_depth:
                        self._fail_counts.popitem(last=False)
                    routed = ForwardFailed(
                        f"request {req.key}: forward failed "
                        f"(consecutive failure {n}): {error}")
                routed.__cause__ = error
            self._settle(req, error=routed)
        with self._cv:
            self._inflight -= 1
            self._g_inflight.set(self._inflight)
            self._cv.notify_all()

    def fail_retryable(self, batch: Batch,
                       cause: Optional[BaseException] = None) -> None:
        """Replica-fault path: a peer died mid-batch.  The dispatched
        batch's requests are failed with :class:`ReplicaFaulted` — a
        RETRYABLE verdict that does NOT charge the quarantine (the fault
        is the world's, not the request's) — while everything still
        queued is left untouched with its ORIGINAL deadline for the
        re-armed serve loop to dispatch after re-rendezvous."""
        for req in batch.requests:
            routed = ReplicaFaulted(
                f"request {req.key}: replica fault mid-batch "
                f"({cause if cause is not None else 'peer lost'}); "
                f"retryable")
            if cause is not None:
                routed.__cause__ = cause
            self._settle(req, error=routed)
        with self._cv:
            self._m_replica_faults.inc()
            self._m_requeued.inc(len(self._queue))
            self._inflight -= 1
            self._g_inflight.set(self._inflight)
            self._cv.notify_all()

    def cancel(self, req: Request) -> bool:
        """Cancel a request that is still QUEUED (a hedge whose twin won):
        removed from the queue and settled with :class:`Cancelled`.
        Returns False — and does nothing — once the request was dispatched
        or settled; an in-flight hedge loser just finishes and its result
        is discarded by the caller."""
        with self._cv:
            if req.done() or req not in self._queue:
                return False
            self._queue.remove(req)
            self._m_cancelled.inc()
            self._g_queue.set(len(self._queue))
            self._settle(req, error=Cancelled(
                f"request {req.key}: cancelled before dispatch"))
            self._cv.notify_all()
        return True

    # -------------------------------------------------------------- drain
    def drain(self) -> None:
        """Stop admitting; queued work still dispatches and settles (the
        elastic drain contract: in-flight requests COMPLETE, new ones are
        refused).  Queued requests whose deadlines have ALREADY expired
        are failed promptly here — dead-on-arrival work completing as a
        late 504 at dispatch time would waste the drain window."""
        with self._cv:
            self._draining = True
            self._expire_locked()
            self._cv.notify_all()

    @property
    def draining(self) -> bool:
        with self._cv:
            return self._draining

    def pending(self) -> int:
        with self._cv:
            return len(self._queue) + self._inflight

    def latency_percentile(self, q: float):
        """Observed request-latency percentile in ms — ``None`` until the
        first success lands (the hedging delay reads this at startup and
        must fall back to its knob, not crash)."""
        return self._m_latency.percentile(q)

    def stats(self) -> dict:
        with self._cv:
            return {
                "queue_depth": len(self._queue),
                "inflight": self._inflight,
                "draining": self._draining,
                "buckets": list(self.buckets),
                "requests_total": self._m_requests.value,
                "rejected_total": self._m_rejected.value,
                "expired_total": self._m_expired.value,
                "batches_total": self._m_batches.value,
                "padding_rows_total": self._m_padding.value,
                "resubmitted_total": self._m_resubmitted.value,
                "quarantined_total": self._m_quarantined.value,
                "replica_faults_total": self._m_replica_faults.value,
                "requeued_total": self._m_requeued.value,
                "cancelled_total": self._m_cancelled.value,
                "latency_p50_ms": self._m_latency.percentile(0.5),
                "latency_p99_ms": self._m_latency.percentile(0.99),
            }
