"""Circuit breaker for the serving front door (no jax, clock-injected).

While a replica is faulted — mid re-rendezvous after a peer death —
every admitted request is doomed to burn its whole deadline in retries.
The breaker converts that into a FAST 503 + ``Retry-After``: callers
learn immediately that the replica is recovering and when to come back,
instead of piling retry load onto a world that is busy healing.

Classic three-state machine, deliberately minimal:

- **closed** — requests flow; ``threshold`` CONSECUTIVE retryable
  failures trip to open (one success resets the streak, so a mixed
  workload never trips on sporadic faults).
- **open** — ``allow()`` refuses everything for ``reset_s`` seconds
  (the front door fast-fails 503 with ``Retry-After`` = the remaining
  window); the clock then half-opens it.
- **half-open** — up to ``probes`` requests are admitted as probes.
  ``probes`` consecutive successes close the breaker (the replica
  healed); ANY failure re-opens it for a fresh ``reset_s``.  A probe
  that terminates with NEITHER verdict (deadline blown, queue full,
  drain race, quarantine — the request's problem, not the replica's)
  must give its slot back via :meth:`~CircuitBreaker.release_probe`;
  as a backstop, probe slots idle for ``reset_s`` are reclaimed by the
  clock so an abandoned probe can never wedge the breaker half-open
  with ``allow()`` refusing forever.

State changes are observable: ``state_code()`` feeds the
``hvd_serve_breaker_state`` gauge (0=closed, 1=open, 2=half-open) and
trips are counted by the front door.  Everything is guarded by one lock
and driven by an injected monotonic clock so the jax-free unit tier can
walk the whole state diagram deterministically.
"""

from __future__ import annotations

import threading
import time
from typing import Callable

CLOSED = "closed"
OPEN = "open"
HALF_OPEN = "half_open"

# Gauge encoding for hvd_serve_breaker_state.
STATE_CODES = {CLOSED: 0, OPEN: 1, HALF_OPEN: 2}


class CircuitBreaker:
    """Consecutive-failure breaker guarding one replica's front door."""

    def __init__(self, threshold: int = 5, reset_s: float = 5.0,
                 probes: int = 2,
                 clock: Callable[[], float] = time.monotonic):
        self.threshold = max(1, int(threshold))
        self.reset_s = max(0.0, float(reset_s))
        self.probes = max(1, int(probes))
        self._clock = clock
        self._lock = threading.Lock()
        self._state = CLOSED
        self._failures = 0          # consecutive, while closed
        self._successes = 0         # consecutive, while half-open
        self._opened_at = 0.0
        self._probes_out = 0        # admitted-but-unresolved half-open probes
        self._probe_activity_at = 0.0   # last half-open admit/resolve time
        self.trips = 0              # lifetime closed/half-open -> open count

    # ------------------------------------------------------------- queries
    @property
    def state(self) -> str:
        with self._lock:
            self._tick_locked()
            return self._state

    def state_code(self) -> int:
        return STATE_CODES[self.state]

    def retry_after_s(self) -> float:
        """Seconds until the breaker half-opens (0 when not open) — the
        front door's ``Retry-After`` while fast-failing."""
        with self._lock:
            self._tick_locked()
            if self._state != OPEN:
                return 0.0
            return max(0.0, self._opened_at + self.reset_s - self._clock())

    # ------------------------------------------------------------ gatework
    def allow(self) -> bool:
        """May a request proceed right now?  Open → no.  Half-open → yes
        for at most ``probes`` unresolved probes at a time."""
        with self._lock:
            self._tick_locked()
            if self._state == CLOSED:
                return True
            if self._state == OPEN:
                return False
            if self._probes_out >= self.probes:
                return False
            self._probes_out += 1
            self._probe_activity_at = self._clock()
            return True

    def record_success(self) -> None:
        with self._lock:
            self._tick_locked()
            if self._state == HALF_OPEN:
                self._probes_out = max(0, self._probes_out - 1)
                self._probe_activity_at = self._clock()
                self._successes += 1
                if self._successes >= self.probes:
                    self._state = CLOSED
                    self._failures = 0
                    self._successes = 0
                    self._probes_out = 0
            else:
                self._failures = 0

    def record_failure(self) -> None:
        """Record one RETRYABLE failure (terminal per-request errors like
        quarantine or deadline are the request's problem, not the
        replica's — callers must not feed them here)."""
        with self._lock:
            self._tick_locked()
            if self._state == HALF_OPEN:
                self._trip_locked()
            elif self._state == CLOSED:
                self._failures += 1
                if self._failures >= self.threshold:
                    self._trip_locked()
            # OPEN: late losers of an already-tripped window change nothing.

    def release_probe(self) -> None:
        """Give back a half-open probe slot whose request terminated with
        NEITHER verdict — deadline blown, queue full, drain race,
        quarantine: the request's problem, not the replica's, so it says
        nothing about heal.  Without the release, ``probes`` such
        outcomes would pin ``_probes_out`` at the cap and ``allow()``
        would refuse forever (504-on-probe is the COMMON case while the
        replica is still re-rendezvousing)."""
        with self._lock:
            self._tick_locked()
            if self._state == HALF_OPEN and self._probes_out > 0:
                self._probes_out -= 1
                self._probe_activity_at = self._clock()

    # ------------------------------------------------------------ internal
    def _trip_locked(self) -> None:
        self._state = OPEN
        self._opened_at = self._clock()
        self._failures = 0
        self._successes = 0
        self._probes_out = 0
        self.trips += 1

    def _tick_locked(self) -> None:
        now = self._clock()
        if self._state == OPEN and now - self._opened_at >= self.reset_s:
            self._state = HALF_OPEN
            self._successes = 0
            self._probes_out = 0
            self._probe_activity_at = now
        elif self._state == HALF_OPEN and self._probes_out > 0 and \
                self.reset_s > 0 and \
                now - self._probe_activity_at >= self.reset_s:
            # Backstop for a probe holder that died without releasing:
            # a slot idle past reset_s is reclaimed so half-open can
            # never wedge with allow() refusing forever.
            self._probes_out = 0
            self._successes = 0
            self._probe_activity_at = now
