"""Replica: the jax-backed half of the serving plane (ISSUE 19).

One :class:`Replica` per process-set member.  Three responsibilities:

- **Weight fan-out** — :meth:`load` broadcasts a parameter pytree from
  the root rank onto every replica via the collective engine's broadcast
  path (:func:`~..jax.optimizer.broadcast_parameters` — which rides the
  hierarchical two-level broadcast when ``HOROVOD_HIERARCHICAL_BROADCAST``
  is on).  Loads are **version-stamped**: a rolling weight update calls
  ``load(params, version=v+1)`` and every replica re-broadcasts without a
  restart, while a redundant re-delivery of the version already serving
  (``version <= self.version``) is a no-op — the idempotence that makes
  "push weights to the fleet, retry on any failure" safe.
- **Bucketed jitted forward** — :meth:`forward` pads a ragged batch up to
  the batcher's bucket size and runs a per-bucket jitted program, cached
  in a :class:`~..ops.scheduler.FusedProgramCache` keyed on
  ``(bucket, per-sample shape, dtype)``.  Parameters are ARGUMENTS to the
  jitted program, so a weight update never recompiles; batch-size churn
  only ever compiles ``len(buckets)`` programs (the cache's hit/miss
  counters prove it, and tests pin it).
- **Serve loop** — :meth:`serve_loop` is the replica's consumer thread:
  ``batcher.next_batch() → pad → forward → slice → complete``, with
  per-batch failures routed back to the callers that sent them rather
  than killing the loop.

Fault tolerance (ISSUE 20): a PEER death mid-batch — the forward rides
collectives in model-parallel serving, and even data-parallel replicas
negotiate the versioned ``load()`` fan-out — surfaces as a typed
:class:`~..common.exceptions.PeerFailureError` (or a clean
:class:`~..common.exceptions.PeerLeftInterrupt`), or as the device
collective failing underneath XLA first when the data plane wins the
race.  :meth:`serve_loop` resolves either against the engine's
control-plane verdict, fails the interrupted batch RETRYABLY
(:meth:`~.batcher.ContinuousBatcher.fail_retryable` — queued requests
keep their original deadlines), and RE-RAISES the typed error so the
worker's elastic wrapper can re-rendezvous and re-arm the loop; the
versioned ``load()`` re-broadcast after heal is a rank-local no-op on
survivors.  Anything else is an application bug in one forward: routed
to that batch's callers (who may retry into the quarantine budget), the
loop keeps serving.
"""

from __future__ import annotations

import threading
import time
from typing import Callable, Optional

import jax
import jax.numpy as jnp
import numpy as np

from ..common.exceptions import HorovodInternalError, HostsUpdatedInterrupt
from ..common.process_sets import ProcessSet
from ..ops.scheduler import FusedProgramCache
from ..testing import faults as _faults
from ..utils.logging import get_logger

log = get_logger()


class Replica:
    """One serving replica: versioned weights + bucket-compiled forward."""

    def __init__(self, apply_fn: Callable, process_set:
                 Optional[ProcessSet] = None, cache_capacity: int = 64):
        self._apply = apply_fn            # (params, inputs[b, ...]) -> out
        self.process_set = process_set
        self.params = None
        self.version = -1                 # nothing loaded yet
        self.loads = 0                    # broadcasts actually executed
        self.cache = FusedProgramCache(capacity=cache_capacity)

    # ------------------------------------------------------------- weights
    def load(self, params, version: int = 0, root_rank: int = 0):
        """Fan ``params`` from ``root_rank`` onto every replica and stamp
        ``version``.  No-op (returns False) when ``version`` does not
        advance — re-delivering the serving version is free, which is what
        lets a rolling updater retry blindly."""
        version = int(version)
        if version <= self.version:
            log.debug("serve: load(version=%d) <= serving version %d — "
                      "no-op", version, self.version)
            return False
        from ..jax.optimizer import broadcast_parameters
        self.params = broadcast_parameters(
            params, root_rank=root_rank, process_set=self.process_set)
        self.version = version
        self.loads += 1
        log.info("serve: weights version %d broadcast from rank %d "
                 "(load #%d)", version, root_rank, self.loads)
        return True

    # ------------------------------------------------------------- forward
    def _program(self, bucket: int, sample_shape: tuple, dtype):
        """The per-bucket jitted forward, cached so batch-size churn
        across requests never recompiles (ISSUE 19 acceptance)."""
        key = ("serve_forward", int(bucket), tuple(sample_shape),
               str(dtype))
        fn, _hit = self.cache.get_or_build2(
            key, lambda: jax.jit(self._apply))
        return fn

    def forward(self, inputs) -> np.ndarray:
        """Run one padded-bucket batch; returns the REAL rows only.

        ``inputs``: array of shape ``[n, *sample]`` with ``n`` anywhere in
        ``(0, bucket]`` — rows are padded with zeros up to the smallest
        power-of-two-ish bucket the cache already compiled for."""
        if self.version < 0:
            raise RuntimeError("serve: forward before load() — no weights")
        x = np.asarray(inputs)
        n = x.shape[0]
        bucket = self._bucket_for(n)
        if bucket > n:
            pad = np.zeros((bucket - n,) + x.shape[1:], dtype=x.dtype)
            x = np.concatenate([x, pad], axis=0)
        fn = self._program(bucket, x.shape[1:], x.dtype)
        out = fn(self.params, jnp.asarray(x))
        return np.asarray(out)[:n]

    def _bucket_for(self, n: int) -> int:
        b = 1
        while b < n:
            b <<= 1
        return b

    def _rank(self) -> int:
        from ..common import basics
        try:
            if basics.is_initialized():
                return basics.rank()
        except Exception:  # noqa: BLE001 - single-process serving
            pass
        return 0

    def forward_batch(self, batch) -> np.ndarray:
        """Batcher-aware forward: pad to the BATCHER's bucket (its menu,
        not the local power-of-two fallback) and slice to real rows."""
        if _faults.armed():
            # Serving chaos verbs (replica_crash / forward_fault /
            # slow_replica) fire HERE — mid-batch, after dispatch, before
            # results route back.  Zero cost unarmed: one module-flag
            # check per BATCH, never per request, never on the control
            # plane.
            _faults.fire("serve_forward", self._rank())
        x = np.stack([np.asarray(r.inputs) for r in batch.requests])
        n = x.shape[0]
        if batch.bucket > n:
            pad = np.zeros((batch.bucket - n,) + x.shape[1:], dtype=x.dtype)
            x = np.concatenate([x, pad], axis=0)
        fn = self._program(batch.bucket, x.shape[1:], x.dtype)
        out = fn(self.params, jnp.asarray(x))
        return np.asarray(out)[:n]

    # ---------------------------------------------------------- serve loop
    def _peer_fault_verdict(self, exc, grace_s: float):
        """Resolve one forward failure against the control plane.

        A dying peer races two planes: the typed HVD303 abort (control)
        and the in-flight device collective failing underneath XLA (data).
        Typed errors ARE the verdict; for anything else, wait up to
        ``grace_s`` for the engine's fault latch to converge — confirmed
        means "the world died", unconfirmed means "this forward is buggy"
        (an application error the quarantine budget handles)."""
        if isinstance(exc, (HorovodInternalError, HostsUpdatedInterrupt)):
            return exc
        try:
            from ..common import basics
            if not basics.is_initialized():
                return None
            eng = basics._get_state().engine
        except Exception:  # noqa: BLE001 - no engine, no verdict
            return None
        deadline = time.monotonic() + max(0.0, grace_s)
        while True:
            fault = getattr(eng, "fault", None)
            if fault is not None:
                return fault
            if time.monotonic() >= deadline:
                return None
            time.sleep(0.05)

    def serve_loop(self, batcher, stop: Optional[threading.Event] = None,
                   poll_s: float = 0.05, fault_grace_s: float = 0.0) -> int:
        """Consume ``batcher`` until ``stop`` is set AND the queue drained
        (or the batcher is draining and empty).  Returns batches served.

        Per-batch APPLICATION errors are routed to the waiting callers
        (``batcher.fail`` — retryable until quarantined), not raised.  A
        PEER FAULT mid-batch fails the interrupted batch retryably,
        leaves queued requests untouched with their original deadlines,
        and re-raises the typed error: the caller re-rendezvouses through
        the elastic path, re-arms via the versioned ``load()`` and runs
        ``serve_loop`` again over the same batcher.  ``fault_grace_s``
        bounds how long an untyped forward failure may wait for the
        control plane's verdict before being treated as an application
        bug (0 = one immediate check)."""
        served = 0
        while True:
            if stop is not None and stop.is_set() and batcher.pending() == 0:
                return served
            batch = batcher.next_batch(timeout=poll_s)
            if batch is None:
                if batcher.draining and batcher.pending() == 0:
                    return served
                continue
            try:
                results = self.forward_batch(batch)
            except Exception as exc:  # noqa: BLE001 - resolved below
                verdict = self._peer_fault_verdict(exc, fault_grace_s)
                if verdict is not None:
                    log.warning(
                        "serve: peer fault mid-batch (%s) — %d request(s) "
                        "failed retryably, %d queued preserved; "
                        "re-rendezvous required",
                        type(verdict).__name__, batch.size,
                        batcher.pending() - 1)
                    batcher.fail_retryable(batch, verdict)
                    raise verdict from exc
                batcher.fail(batch, exc)
                continue
            batcher.complete(batch, list(results))
            served += 1
