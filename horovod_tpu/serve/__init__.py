"""Data-parallel serving plane (ISSUE 19, ``docs/serving.md``).

The inference-time face of the collective runtime: the same process-set
fabric that synchronizes training replicas fans a trained model out to N
serving replicas and keeps their weights in lock-step through rolling
updates, while a jax-free front door admits requests with continuous
batching, padded-bucket shapes, deadlines and backpressure.

Three layers, matching the training stack's jax-free/jax-backed split:

- :class:`~.batcher.ContinuousBatcher` — admission queue + padded-bucket
  batch former; ``HOROVOD_MAX_INFLIGHT``-style bounded dispatch window
  (``batcher.py``, stdlib only).
- :class:`~.frontdoor.FrontDoor` — HTTP/in-process ingest mapping
  overload → 429, draining → 503, blown deadline → 504; ``drain()``
  flips the monitor's ``/ready`` latch (``frontdoor.py``, stdlib only).
- :class:`Replica` — version-stamped ``broadcast_parameters`` weight
  fan-out + per-bucket jitted forward keyed into the
  ``FusedProgramCache`` (``replica.py``, imports jax; loads lazily here
  via PEP 562 so the jax-free tier can import ``horovod_tpu.serve``).

Knob table (``HOROVOD_SERVE_*``) lives in ``common/config.py`` and
``docs/serving.md``; ``torovodrun --serve`` wires it end-to-end.
"""

from .batcher import (  # noqa: F401  (jax-free re-exports)
    Batch, Cancelled, ContinuousBatcher, DeadlineExceeded, Draining,
    ForwardFailed, QueueFull, ReplicaFaulted, Request, RequestQuarantined,
    Retryable, parse_buckets,
)
from .frontdoor import FrontDoor  # noqa: F401
from .resilience import CircuitBreaker  # noqa: F401

# Lazily-loaded jax-backed replica layer (serve/replica.py imports jax).
_REPLICA_ATTRS = ("Replica",)


def __getattr__(name):
    if name in _REPLICA_ATTRS:
        from . import replica as _replica
        return getattr(_replica, name)
    raise AttributeError(f"module {__name__!r} has no attribute {name!r}")


def __dir__():
    return sorted(set(globals()) | set(_REPLICA_ATTRS))
