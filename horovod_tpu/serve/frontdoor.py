"""HTTP/in-process ingest for the serving plane (no jax imports).

The jax-free front half of ``horovod_tpu.serve`` (ISSUE 19,
``docs/serving.md``): a stdlib ``ThreadingHTTPServer`` that feeds the
:class:`~.batcher.ContinuousBatcher` and maps its refusals onto the HTTP
status codes load balancers already understand:

- ``POST /v1/infer``  — ``{"inputs": [...], "deadline_ms": 250}`` →
  ``200 {"outputs": ..., "latency_ms": ...}``.  Overload → **429** with
  ``Retry-After`` and the live queue depth (the backpressure signal);
  draining → **503**; deadline blown → **504**.
- ``GET /v1/stats``   — the batcher's counters/percentiles as JSON (what
  ``bench.py serving`` and operators poll).

Readiness integration: :meth:`drain` stops admission AND flips the rank's
:class:`~..monitor.agent.MonitorAgent` readiness latch, so the LB's
``/ready`` probe (monitor HTTP server) goes 503 the moment the elastic
driver cordons this replica — in-flight requests still complete.

Deliberately per-replica: each replica runs its own front door and an
external load balancer spreads requests across replicas using ``/ready``.
The collective plane (weight fan-out, telemetry aggregation) is the only
cross-replica traffic.
"""

from __future__ import annotations

import json
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Optional

from .batcher import ContinuousBatcher, Draining, QueueFull
from ..utils.logging import get_logger

log = get_logger()


class FrontDoor:
    """One replica's ingest surface: HTTP + in-process ``infer()``."""

    def __init__(self, batcher: ContinuousBatcher, port: int = 0,
                 addr: str = "", agent=None):
        self.batcher = batcher
        self._agent = agent
        outer = self

        class Handler(BaseHTTPRequestHandler):
            def log_message(self, *a):  # silence stdlib request logging
                pass

            def _send(self, code: int, obj: dict, retry_after=None):
                data = json.dumps(obj).encode()
                self.send_response(code)
                self.send_header("Content-Type", "application/json")
                self.send_header("Content-Length", str(len(data)))
                if retry_after is not None:
                    self.send_header("Retry-After", str(retry_after))
                self.end_headers()
                self.wfile.write(data)

            def do_GET(self):  # noqa: N802 - stdlib API
                try:
                    if self.path.split("?", 1)[0] == "/v1/stats":
                        self._send(200, outer.batcher.stats())
                    else:
                        self._send(404, {"error": "try /v1/stats or "
                                                  "POST /v1/infer"})
                except BrokenPipeError:  # pragma: no cover - client gone
                    pass

            def do_POST(self):  # noqa: N802 - stdlib API
                try:
                    if self.path.split("?", 1)[0] != "/v1/infer":
                        self._send(404, {"error": "POST /v1/infer"})
                        return
                    n = int(self.headers.get("Content-Length") or 0)
                    try:
                        body = json.loads(self.rfile.read(n) or b"{}")
                    except ValueError:
                        self._send(400, {"error": "invalid JSON"})
                        return
                    if "inputs" not in body:
                        self._send(400, {"error": "missing 'inputs'"})
                        return
                    out = outer.infer_detailed(
                        body["inputs"], body.get("deadline_ms"))
                    self._send(out.pop("_code"), out,
                               retry_after=out.pop("_retry_after", None))
                except BrokenPipeError:  # pragma: no cover - client gone
                    pass
                except Exception as exc:  # noqa: BLE001 - keep serving
                    try:
                        self._send(500, {"error": str(exc)})
                    except Exception:  # pragma: no cover
                        pass

        self._httpd = ThreadingHTTPServer((addr, int(port)), Handler)
        self._httpd.daemon_threads = True
        self.port = self._httpd.server_address[1]
        self._thread: Optional[threading.Thread] = None

    # ------------------------------------------------------------- ingest
    def infer_detailed(self, inputs, deadline_ms=None) -> dict:
        """One request end-to-end; returns a JSON-able dict carrying the
        HTTP status in ``_code`` (shared by the HTTP handler and tests)."""
        b = self.batcher
        try:
            req = b.submit(inputs, deadline_ms=deadline_ms)
        except QueueFull:
            return {"_code": 429, "_retry_after": 1,
                    "error": "queue full",
                    "queue_depth": b.stats()["queue_depth"]}
        except Draining:
            return {"_code": 503, "error": "draining"}
        ttl = (b.deadline_ms if deadline_ms is None
               else float(deadline_ms)) / 1000.0
        try:
            result = req.wait(timeout=ttl + 0.25)
        except Exception as exc:  # noqa: BLE001 - routed per-request error
            code = 504 if "expired" in str(exc) or "within" in str(exc) \
                else 500
            return {"_code": code, "error": str(exc)}
        outputs = result.tolist() if hasattr(result, "tolist") else result
        return {"_code": 200, "outputs": outputs,
                "latency_ms": round(
                    (req.completed_at - req.enqueued_at) * 1e3, 3)}

    def infer(self, inputs, deadline_ms=None):
        """In-process convenience: result or raised error."""
        out = self.infer_detailed(inputs, deadline_ms=deadline_ms)
        if out["_code"] != 200:
            raise RuntimeError(f"infer failed ({out['_code']}): "
                               f"{out.get('error')}")
        return out["outputs"]

    # ----------------------------------------------------------- lifecycle
    def start(self) -> "FrontDoor":
        self._thread = threading.Thread(
            target=self._httpd.serve_forever, name="hvd-tpu-serve-http",
            daemon=True)
        self._thread.start()
        log.info("serve: front door listening on :%d "
                 "(POST /v1/infer, GET /v1/stats)", self.port)
        return self

    def drain(self) -> None:
        """Cordon this replica: refuse new work, flip ``/ready`` to 503,
        let queued/in-flight requests complete."""
        self.batcher.drain()
        if self._agent is not None:
            try:
                self._agent.set_ready(
                    False, "draining: serve front door cordoned")
            except Exception:  # noqa: BLE001 - telemetry never blocks
                pass

    def stop(self) -> None:
        try:
            # shutdown() BLOCKS until serve_forever exits — only safe when
            # start() actually ran; a never-started server just closes.
            if self._thread is not None:
                self._httpd.shutdown()
            self._httpd.server_close()
        except Exception:  # noqa: BLE001 - already down
            pass
        if self._thread is not None:
            self._thread.join(timeout=5)
            self._thread = None
