"""HTTP/in-process ingest for the serving plane (no jax imports).

The jax-free front half of ``horovod_tpu.serve`` (ISSUE 19/20,
``docs/serving.md``): a stdlib ``ThreadingHTTPServer`` that feeds the
:class:`~.batcher.ContinuousBatcher` and maps its refusals onto the HTTP
status codes load balancers already understand:

- ``POST /v1/infer``  — ``{"inputs": [...], "deadline_ms": 250}`` →
  ``200 {"outputs": ..., "latency_ms": ...}``.  Overload → **429** with
  ``Retry-After`` and the live queue depth (the backpressure signal);
  draining → **503** + ``Retry-After`` (drain is transient); deadline
  blown → **504**.
- ``GET /v1/stats``   — batcher counters/percentiles plus the fault-
  tolerance surface (breaker state, retry/hedge/quarantine counters,
  availability) as JSON.

Fault tolerance (ISSUE 20) — the hard invariant is that every ACCEPTED
request gets exactly one terminal response, no matter what dies:

- **Retries** — retryable failures (:class:`~.batcher.Retryable`: a
  replica peer fault mid-batch, a transient forward fault) are retried
  through :func:`~..common.net.retry_with_backoff` with capped
  exponential backoff + jitter.  Backoff is charged against the
  request's ORIGINAL deadline: an attempt whose backoff would outlive
  the deadline is abandoned immediately (504), never extended.
- **Idempotent re-submission** — every request carries an id; the
  batcher's resident-request map joins a retry to its own still-live
  earlier attempt instead of double-executing it.
- **Hedging** (``HOROVOD_SERVE_HEDGE_MS`` > 0) — when the primary
  attempt is slower than the observed p99 (the knob is the cold-start
  fallback while the latency histogram is empty and ``percentile``
  returns ``None``), a duplicate is dispatched under a twin id; the
  first terminal response wins and the loser is cancelled.
- **Circuit breaker** — consecutive retryable failures trip a
  :class:`~.resilience.CircuitBreaker`; while open, requests fast-fail
  **503** + ``Retry-After`` (the remaining open window) instead of
  burning their deadlines against a replica that is mid-heal; probes
  half-open it and successes close it.

Readiness integration: :meth:`drain` stops admission AND flips the rank's
:class:`~..monitor.agent.MonitorAgent` readiness latch, so the LB's
``/ready`` probe (monitor HTTP server) goes 503 the moment the elastic
driver cordons this replica — in-flight requests still complete.

Deliberately per-replica: each replica runs its own front door and an
external load balancer spreads requests across replicas using ``/ready``.
The collective plane (weight fan-out, telemetry aggregation) is the only
cross-replica traffic — every knob here is serve-local and adds zero
bytes to the warm control-plane frame.
"""

from __future__ import annotations

import itertools
import json
import math
import os
import threading
import time
import uuid
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Optional

from .batcher import (
    ContinuousBatcher, DeadlineExceeded, Draining, QueueFull,
    ReplicaFaulted, RequestQuarantined, Retryable,
)
from .resilience import CircuitBreaker
from ..common.net import retry_with_backoff
from ..utils.logging import get_logger

log = get_logger()

# Drain is transient (rolling update / scale-in): tell the LB when to
# probe again instead of leaving 503 ambiguous with overload.
DRAIN_RETRY_AFTER_S = 5

# Retry backoff envelope (milliseconds).  Small on purpose: serving
# deadlines are sub-second to seconds, and backoff is charged against
# the request's own deadline.
RETRY_BASE_MS = 25.0
RETRY_MAX_MS = 1000.0


def _env_int(name: str, default: int) -> int:
    try:
        return int(os.environ.get(name, "") or default)
    except ValueError:
        return default


def _env_float(name: str, default: float) -> float:
    try:
        return float(os.environ.get(name, "") or default)
    except ValueError:
        return default


class FrontDoor:
    """One replica's ingest surface: HTTP + in-process ``infer()``."""

    _rids = itertools.count()

    def __init__(self, batcher: ContinuousBatcher, port: int = 0,
                 addr: str = "", agent=None, retries: Optional[int] = None,
                 hedge_ms: Optional[float] = None,
                 breaker: Optional[CircuitBreaker] = None,
                 slo: Optional[float] = None,
                 clock=time.monotonic):
        self.batcher = batcher
        self._agent = agent
        self._clock = clock
        self.retries = (_env_int("HOROVOD_SERVE_RETRIES", 2)
                        if retries is None else max(0, int(retries)))
        self.hedge_ms = (_env_float("HOROVOD_SERVE_HEDGE_MS", 0.0)
                         if hedge_ms is None else float(hedge_ms))
        self.slo = (_env_float("HOROVOD_SERVE_SLO", 0.999)
                    if slo is None else float(slo))
        self.breaker = breaker if breaker is not None else CircuitBreaker(
            threshold=_env_int("HOROVOD_SERVE_BREAKER_THRESHOLD", 5),
            reset_s=_env_float("HOROVOD_SERVE_BREAKER_RESET_S", 5.0),
            probes=_env_int("HOROVOD_SERVE_BREAKER_PROBES", 2),
            clock=clock)
        reg = batcher.registry
        self._m_retries = reg.counter(
            "hvd_serve_retries_total", "front-door retry attempts")
        self._m_hedges = reg.counter(
            "hvd_serve_hedges_total", "hedged (duplicate) dispatches")
        self._m_hedge_wins = reg.counter(
            "hvd_serve_hedge_wins_total",
            "requests whose hedge twin finished first")
        self._m_breaker_open = reg.counter(
            "hvd_serve_breaker_open_total", "circuit-breaker trips")
        self._m_fastfail = reg.counter(
            "hvd_serve_breaker_fastfail_total",
            "requests fast-failed 503 while the breaker was open")
        self._m_ok = reg.counter(
            "hvd_serve_responses_ok_total", "terminal 200 responses")
        self._m_err = reg.counter(
            "hvd_serve_responses_error_total",
            "terminal error responses counted against the error budget "
            "(500/504 and non-drain 503)")
        self._g_breaker = reg.gauge(
            "hvd_serve_breaker_state",
            "circuit breaker: 0=closed 1=open 2=half-open")
        self._g_avail = reg.gauge(
            "hvd_serve_availability",
            "terminal-response availability (ok / (ok + error))")
        self._g_budget = reg.gauge(
            "hvd_serve_error_budget_remaining",
            "fraction of the SLO error budget left (negative = blown)")
        self._g_avail.set(1.0)
        self._g_budget.set(1.0)
        self._breaker_sync_lock = threading.Lock()
        outer = self

        class Handler(BaseHTTPRequestHandler):
            def log_message(self, *a):  # silence stdlib request logging
                pass

            def _send(self, code: int, obj: dict, retry_after=None):
                data = json.dumps(obj).encode()
                self.send_response(code)
                self.send_header("Content-Type", "application/json")
                self.send_header("Content-Length", str(len(data)))
                if retry_after is not None:
                    self.send_header("Retry-After", str(retry_after))
                self.end_headers()
                self.wfile.write(data)

            def do_GET(self):  # noqa: N802 - stdlib API
                try:
                    if self.path.split("?", 1)[0] == "/v1/stats":
                        self._send(200, outer.stats())
                    else:
                        self._send(404, {"error": "try /v1/stats or "
                                                  "POST /v1/infer"})
                except BrokenPipeError:  # pragma: no cover - client gone
                    pass

            def do_POST(self):  # noqa: N802 - stdlib API
                try:
                    if self.path.split("?", 1)[0] != "/v1/infer":
                        self._send(404, {"error": "POST /v1/infer"})
                        return
                    n = int(self.headers.get("Content-Length") or 0)
                    try:
                        body = json.loads(self.rfile.read(n) or b"{}")
                    except ValueError:
                        self._send(400, {"error": "invalid JSON"})
                        return
                    if "inputs" not in body:
                        self._send(400, {"error": "missing 'inputs'"})
                        return
                    out = outer.infer_detailed(
                        body["inputs"], body.get("deadline_ms"),
                        request_id=body.get("request_id"))
                    self._send(out.pop("_code"), out,
                               retry_after=out.pop("_retry_after", None))
                except BrokenPipeError:  # pragma: no cover - client gone
                    pass
                except Exception as exc:  # noqa: BLE001 - keep serving
                    try:
                        self._send(500, {"error": str(exc)})
                    except Exception:  # pragma: no cover
                        pass

        self._httpd = ThreadingHTTPServer((addr, int(port)), Handler)
        self._httpd.daemon_threads = True
        self.port = self._httpd.server_address[1]
        self._thread: Optional[threading.Thread] = None

    # ------------------------------------------------------------- ingest
    def infer_detailed(self, inputs, deadline_ms=None,
                       request_id=None) -> dict:
        """One request end-to-end — admission, retries, hedging, breaker —
        returning a JSON-able dict carrying the HTTP status in ``_code``
        (shared by the HTTP handler and tests).  Exactly one terminal
        outcome per call, bounded by the request's original deadline."""
        b = self.batcher
        ttl_s = (b.deadline_ms if deadline_ms is None
                 else float(deadline_ms)) / 1000.0
        deadline = self._clock() + ttl_s
        rid = (str(request_id) if request_id
               else f"fd-{next(FrontDoor._rids)}-{uuid.uuid4().hex[:8]}")

        if not self.breaker.allow():
            self._m_fastfail.inc()
            self._sync_breaker_gauge()
            ra = max(1, math.ceil(self.breaker.retry_after_s() or 1.0))
            return self._finish({
                "_code": 503, "_retry_after": ra, "request_id": rid,
                "error": "circuit open: replica faulted, healing",
                "breaker": self.breaker.state, "retryable": True})

        attempts = {"n": 0}
        # Did any attempt deliver a breaker verdict?  Terminal outcomes
        # that say nothing about replica health (deadline, queue full,
        # drain, quarantine) must RELEASE an admitted half-open probe
        # slot instead of leaking it — see the finally below.
        verdict = {"recorded": False}

        def attempt():
            attempts["n"] += 1
            remaining = deadline - self._clock()
            if remaining <= 0:
                raise DeadlineExceeded(
                    f"request {rid}: deadline exhausted before attempt "
                    f"{attempts['n']}")
            # Re-submission under the SAME id: the batcher's resident map
            # joins a still-live earlier attempt instead of forking it,
            # and the shrunken remaining ttl keeps the absolute deadline
            # fixed across attempts.
            req = b.submit(inputs, deadline_ms=remaining * 1000.0,
                           request_id=rid)
            try:
                winner, result = self._await(req, rid)
            except Retryable:
                verdict["recorded"] = True
                self.breaker.record_failure()
                self._sync_breaker_gauge()
                raise
            verdict["recorded"] = True
            self.breaker.record_success()
            self._sync_breaker_gauge()
            return winner, result

        def on_retry(n, exc, delay_s):
            # Deadline accounting: backoff that would outlive the
            # request's deadline is not taken — the pending retryable
            # error becomes the terminal response instead.
            if self._clock() + delay_s >= deadline:
                raise exc
            self._m_retries.inc()

        try:
            req, result = retry_with_backoff(
                attempt, retries=self.retries, base_ms=RETRY_BASE_MS,
                max_ms=RETRY_MAX_MS, exceptions=(Retryable,),
                on_retry=on_retry)
        except QueueFull:
            return self._finish({
                "_code": 429, "_retry_after": 1, "request_id": rid,
                "error": "queue full",
                "queue_depth": b.stats()["queue_depth"]})
        except Draining:
            return self._finish({
                "_code": 503, "_retry_after": DRAIN_RETRY_AFTER_S,
                "request_id": rid, "error": "draining", "draining": True})
        except RequestQuarantined as exc:
            return self._finish({
                "_code": 500, "request_id": rid, "error": str(exc),
                "quarantined": True})
        except ReplicaFaulted as exc:
            return self._finish({
                "_code": 503, "_retry_after": 1, "request_id": rid,
                "error": str(exc), "retryable": True,
                "attempts": attempts["n"]})
        except Retryable as exc:
            return self._finish({
                "_code": 500, "request_id": rid, "error": str(exc),
                "retryable": True, "attempts": attempts["n"]})
        except DeadlineExceeded as exc:
            return self._finish({
                "_code": 504, "request_id": rid, "error": str(exc)})
        except Exception as exc:  # noqa: BLE001 - routed per-request error
            code = 504 if "expired" in str(exc) or "within" in str(exc) \
                else 500
            return self._finish({
                "_code": code, "request_id": rid, "error": str(exc)})
        finally:
            if not verdict["recorded"]:
                self.breaker.release_probe()
                self._sync_breaker_gauge()
        outputs = result.tolist() if hasattr(result, "tolist") else result
        return self._finish({
            "_code": 200, "outputs": outputs, "request_id": rid,
            "attempts": attempts["n"],
            "latency_ms": round(
                (req.completed_at - req.enqueued_at) * 1e3, 3)})

    def _await(self, req, rid: str):
        """Wait one attempt out, hedging the tail when enabled: if the
        primary is slower than the observed p99 (``hedge_ms`` is the
        cold-start fallback while the histogram is empty), dispatch a
        duplicate under a twin id; first terminal response wins, the
        loser is cancelled (queued) or discarded (in flight).  Returns
        ``(winning_request, result)`` so the caller reports the winner's
        latency."""
        b = self.batcher
        remaining = max(0.0, req.deadline - self._clock())
        delay_s = self._hedge_delay_s(remaining)
        if delay_s is None:
            return req, self._wait_or_cancel(req, remaining + 0.25)
        try:
            return req, req.wait(timeout=delay_s)
        except DeadlineExceeded:
            if req.done():          # settled at the boundary: routed error
                return req, req.wait(0)
        remaining = max(0.0, req.deadline - self._clock())
        try:
            hedge = b.submit(req.inputs, deadline_ms=remaining * 1000.0,
                             request_id=rid + ".hedge")
        except (QueueFull, Draining):
            # No room to hedge — keep waiting on the primary.
            return req, self._wait_or_cancel(req, remaining + 0.25)
        self._m_hedges.inc()
        settled = threading.Event()
        req.on_done(lambda _r: settled.set())
        hedge.on_done(lambda _r: settled.set())
        end = self._clock() + remaining + 0.25
        while not (req.done() or hedge.done()):
            left = end - self._clock()
            if left <= 0:
                break
            settled.wait(min(left, 0.05))
        if req.done() and (not hedge.done() or req.error is None
                           or hedge.error is not None):
            winner, loser = req, hedge
        elif hedge.done():
            winner, loser = hedge, req
        else:
            # Terminal timeout: cancel BOTH twins, not just the hedge —
            # a primary left resident would absorb a client re-submission
            # under the same id (submit joins resident entries, ignoring
            # the fresh deadline) and doom it to another 504.
            b.cancel(hedge)
            b.cancel(req)
            raise DeadlineExceeded(
                f"request {rid}: no result within {remaining:.3f}s")
        if winner is hedge:
            self._m_hedge_wins.inc()
        b.cancel(loser)
        return winner, winner.wait(0)

    def _wait_or_cancel(self, req, timeout_s: float):
        """``req.wait`` that cancels the request on ITS OWN timeout, so a
        timed-out-but-still-queued request does not stay resident to
        swallow a client re-submission under the same id."""
        try:
            return req.wait(timeout=timeout_s)
        except DeadlineExceeded:
            self.batcher.cancel(req)
            raise

    def _hedge_delay_s(self, remaining_s: float) -> Optional[float]:
        if self.hedge_ms <= 0:
            return None
        p99 = self.batcher.latency_percentile(0.99)
        delay_ms = self.hedge_ms if p99 is None else max(float(p99), 1.0)
        delay_s = delay_ms / 1000.0
        if delay_s >= remaining_s:
            return None             # no deadline room left to hedge in
        return delay_s

    def infer(self, inputs, deadline_ms=None, request_id=None):
        """In-process convenience: result or raised error."""
        out = self.infer_detailed(inputs, deadline_ms=deadline_ms,
                                  request_id=request_id)
        if out["_code"] != 200:
            raise RuntimeError(f"infer failed ({out['_code']}): "
                               f"{out.get('error')}")
        return out["outputs"]

    # ---------------------------------------------------------- telemetry
    def _sync_breaker_gauge(self) -> None:
        # One lock around the read-then-inc: two handler threads racing
        # the naive `while value < trips: inc()` loop would both observe
        # the gap and over-count a Counter that can never be corrected.
        with self._breaker_sync_lock:
            self._g_breaker.set(self.breaker.state_code())
            delta = self.breaker.trips - self._m_breaker_open.value
            if delta > 0:
                self._m_breaker_open.inc(delta)

    def _finish(self, out: dict) -> dict:
        """Classify the terminal response into the availability gauges.
        429 (backpressure), 400 (caller bug) and drain 503 are not
        service errors; breaker/fault 503, 500 and 504 are."""
        code = out["_code"]
        if code == 200:
            self._m_ok.inc()
        elif code in (500, 504) or (code == 503 and not out.get("draining")):
            self._m_err.inc()
        ok, err = self._m_ok.value, self._m_err.value
        total = ok + err
        if total:
            avail = ok / total
            self._g_avail.set(round(avail, 6))
            budget = 1.0 - self.slo
            if budget > 0:
                self._g_budget.set(
                    round(1.0 - (1.0 - avail) / budget, 6))
        return out

    def stats(self) -> dict:
        """Batcher counters plus the fault-tolerance surface (what
        ``GET /v1/stats`` serves)."""
        out = self.batcher.stats()
        out.update({
            "breaker_state": self.breaker.state,
            "breaker_trips": self.breaker.trips,
            "retries_total": self._m_retries.value,
            "hedges_total": self._m_hedges.value,
            "hedge_wins_total": self._m_hedge_wins.value,
            "responses_ok_total": self._m_ok.value,
            "responses_error_total": self._m_err.value,
            "availability": self._g_avail.value,
            "error_budget_remaining": self._g_budget.value,
        })
        return out

    # ----------------------------------------------------------- lifecycle
    def start(self) -> "FrontDoor":
        self._thread = threading.Thread(
            target=self._httpd.serve_forever, name="hvd-tpu-serve-http",
            daemon=True)
        self._thread.start()
        log.info("serve: front door listening on :%d "
                 "(POST /v1/infer, GET /v1/stats)", self.port)
        return self

    def drain(self) -> None:
        """Cordon this replica: refuse new work, flip ``/ready`` to 503,
        let queued/in-flight requests complete."""
        self.batcher.drain()
        if self._agent is not None:
            try:
                self._agent.set_ready(
                    False, "draining: serve front door cordoned")
            except Exception:  # noqa: BLE001 - telemetry never blocks
                pass

    def stop(self) -> None:
        try:
            # shutdown() BLOCKS until serve_forever exits — only safe when
            # start() actually ran; a never-started server just closes.
            if self._thread is not None:
                self._httpd.shutdown()
            self._httpd.server_close()
        except Exception:  # noqa: BLE001 - already down
            pass
        if self._thread is not None:
            self._thread.join(timeout=5)
            self._thread = None
