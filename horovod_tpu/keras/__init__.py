"""Keras binding: ``import horovod_tpu.keras as hvd``.

Parity with the reference's Keras API (``horovod/keras/`` +
``horovod/_keras/`` — SURVEY.md §2b P5): ``DistributedOptimizer`` (shared
with the TF binding — it already dynamically subclasses the Keras optimizer
class so ``model.compile`` accepts it), ``broadcast_global_variables``, and
the Keras callbacks (:mod:`horovod_tpu.keras.callbacks`).

Works with Keras 3 (``keras.Model.fit``): gradient reductions run as
``tf.py_function`` bodies inside the compiled train step, so no
``run_eagerly=True`` is required.
"""

from __future__ import annotations

from ..common.basics import (  # noqa: F401
    init, shutdown, is_initialized, rank, size, local_rank, local_size,
    cross_rank, cross_size,
)
from ..tensorflow import (  # noqa: F401
    Average, Compression, Max, Min, Product, ReduceOp, Sum,
    DistributedOptimizer, allgather, allreduce, broadcast, broadcast_object,
    broadcast_variables,
)
from . import callbacks  # noqa: F401


def broadcast_global_variables(model, root_rank: int = 0):
    """Broadcast a model's (and, when built, its optimizer's) variables
    from ``root_rank`` (reference: ``hvd.keras.broadcast_global_variables``)."""
    variables = list(model.weights)
    opt = getattr(model, "optimizer", None)
    if opt is not None:
        # Keras 3 exposes ``optimizer.variables`` as a property; legacy
        # tf.keras (Keras 2) optimizers expose it as a bound method.
        opt_vars = getattr(opt, "variables", None)
        if callable(opt_vars):
            opt_vars = opt_vars()
        variables += list(opt_vars or [])
    broadcast_variables(variables, root_rank=root_rank)
