"""Keras callbacks (reference: ``horovod/_keras/callbacks.py`` —
SURVEY.md §2b P5).

These adapt the framework-generic policies in ``horovod_tpu/callbacks.py``
to real ``keras.callbacks.Callback`` hooks so they attach to
``model.fit(...)`` directly.
"""

from __future__ import annotations

import math
from typing import Optional

import keras
import numpy as np

from ..common import basics
from ..ops import collectives as C
from ..ops import eager


class BroadcastGlobalVariablesCallback(keras.callbacks.Callback):
    """Broadcast rank 0's model + optimizer state at train start
    (reference: ``BroadcastGlobalVariablesCallback``) so all ranks begin
    from identical initialization / restored checkpoints."""

    def __init__(self, root_rank: int = 0):
        super().__init__()
        self.root_rank = root_rank
        self._done = False

    def on_batch_end(self, batch, logs=None):
        # End of the FIRST batch: the optimizer's slot variables now exist,
        # so momentum state broadcasts too (the reference hooks the same
        # point for the same reason).  Every later step applies identical
        # reduced gradients, so ranks stay in lock-step from here.
        if self._done or basics.size() <= 1:
            return
        from . import broadcast_global_variables
        broadcast_global_variables(self.model, self.root_rank)
        self._done = True


class MetricAverageCallback(keras.callbacks.Callback):
    """Average epoch metrics over all ranks (reference:
    ``MetricAverageCallback``) so logged/early-stopping values reflect the
    global job, not one rank's shard."""

    def on_epoch_end(self, epoch, logs=None):
        if not logs or basics.size() <= 1:
            return
        keys = sorted(k for k, v in logs.items()
                      if isinstance(v, (int, float, np.floating)))
        if not keys:
            return
        vec = np.asarray([float(logs[k]) for k in keys], np.float64)
        out = eager.allreduce(
            vec if eager.per_process_mode()
            else np.broadcast_to(vec, (basics.size(),) + vec.shape),
            name=f"metric_avg.{epoch}", op=C.ReduceOp.AVERAGE)
        avg = np.asarray(eager.to_local(out)).reshape(-1)
        for k, v in zip(keys, avg):
            logs[k] = float(v)


class LearningRateScheduleCallback(keras.callbacks.Callback):
    """Multiply the LR by ``multiplier(epoch)`` within an epoch range
    (reference: ``LearningRateScheduleCallback``)."""

    def __init__(self, initial_lr: float, multiplier, start_epoch: int = 0,
                 end_epoch: Optional[int] = None, staircase: bool = True):
        super().__init__()
        self.initial_lr = initial_lr
        self.start_epoch = start_epoch
        self.end_epoch = end_epoch
        self.staircase = staircase
        self.multiplier = (multiplier if callable(multiplier)
                           else (lambda epoch: multiplier))

    def _in_range(self, epoch: int) -> bool:
        return epoch >= self.start_epoch and (
            self.end_epoch is None or epoch < self.end_epoch)

    def on_epoch_begin(self, epoch, logs=None):
        if self._in_range(epoch):
            lr = self.initial_lr * float(self.multiplier(epoch))
            self.model.optimizer.learning_rate.assign(lr)


class LearningRateWarmupCallback(LearningRateScheduleCallback):
    """Linear warmup from ``initial_lr`` to ``initial_lr * size()`` over
    ``warmup_epochs`` (reference: ``LearningRateWarmupCallback`` — the
    'scale LR by world size, warm up to it' large-batch recipe)."""

    def __init__(self, initial_lr: float, warmup_epochs: int = 5,
                 momentum_correction: bool = True, verbose: int = 0):
        self.warmup_epochs = warmup_epochs
        self.verbose = verbose
        if momentum_correction:
            # Accepted for reference API parity; the reference rescales SGD
            # momentum as the LR steps during warmup, which this binding
            # does not implement — say so instead of silently differing.
            import warnings
            warnings.warn(
                "LearningRateWarmupCallback: momentum_correction is not "
                "applied in horovod_tpu (pass momentum_correction=False to "
                "silence); training dynamics during warmup may differ "
                "slightly from reference Horovod with momentum optimizers",
                stacklevel=2)
        world = basics.size()

        def multiplier(epoch):
            if warmup_epochs <= 0:
                return world
            progress = min(1.0, (epoch + 1) / float(warmup_epochs))
            return 1.0 + progress * (world - 1.0)

        super().__init__(initial_lr=initial_lr, multiplier=multiplier,
                         start_epoch=0, end_epoch=warmup_epochs)

    def on_epoch_begin(self, epoch, logs=None):
        super().on_epoch_begin(epoch, logs)
        if self.verbose and epoch < self.warmup_epochs:
            lr = float(self.model.optimizer.learning_rate.numpy())
            print(f"Epoch {epoch}: LearningRateWarmupCallback lr={lr:.6f}")
