"""AST linter for deadlock-prone collective patterns (rules HVD101–HVD107).

The static half of what the reference's controller + stall inspector catch
at runtime (SURVEY.md §L2): ranks disagreeing on the sequence, signature or
process set of a collective.  Works on source text only — no jax import, no
initialization — so it can gate CI and be run over user training scripts
before a job ever touches a TPU.

Suppression: a ``# hvd-lint: disable=HVD101`` comment on the flagged line
(or comma-separated IDs, or ``disable=all``) silences that line.
"""

from __future__ import annotations

import ast
import io
import os
import re
import tokenize
from typing import Dict, Iterable, List, Optional, Sequence, Set, Tuple

from .findings import Finding, Severity

# ---------------------------------------------------------------------------
# Name tables
# ---------------------------------------------------------------------------

# Every public spelling of a collective submission across the bindings
# (ops/eager.py, torch/mpi_ops.py, tensorflow/mpi_ops.py, jax/optimizer.py).
_BASE_COLLECTIVES = {
    "allreduce", "allgather", "broadcast", "alltoall", "reducescatter",
    "barrier",
}
COLLECTIVE_NAMES: Set[str] = set()
for _b in _BASE_COLLECTIVES:
    for _v in (_b, f"{_b}_", f"{_b}_async", f"{_b}_async_",
               f"grouped_{_b}", f"grouped_{_b}_async",
               f"grouped_{_b}_async_", f"grouped_{_b}_"):
        COLLECTIVE_NAMES.add(_v)
# NB: hvd.join() is deliberately NOT here — it is the sanctioned way for
# ranks to stop submitting at different times (uneven final batches), so
# rank-divergent calls to it are correct, not a bug.
COLLECTIVE_NAMES |= {
    "broadcast_object", "allgather_object", "broadcast_pytree",
    "broadcast_parameters", "broadcast_optimizer_state",
    "broadcast_variables", "allreduce_gradients",
}

# Functions that perform the rank-0 state sync HVD103 wants to see.
_SYNC_CALLS = {
    "broadcast_parameters", "broadcast_optimizer_state",
    "broadcast_variables", "broadcast_object", "broadcast_pytree",
    "BroadcastGlobalVariablesCallback",
}

# Rank-identity accessors whose results make control flow rank-divergent.
_RANK_CALLS = {"rank", "local_rank", "cross_rank", "process_index"}

# Host-sync / callback spellings flagged inside jit (HVD106).
_HOST_SYNC_CALLS = {
    "block_until_ready", "io_callback", "pure_callback", "call_host",
    "host_callback", "device_get",
}

# Gradient-reducing wrappers whose presence means "this is a training
# script" for HVD103.
_TRAINING_WRAPPERS = {
    "DistributedOptimizer", "DistributedGradientTape", "allreduce_gradients",
}

_DISABLE_RE = re.compile(r"hvd-lint\s*:\s*disable\s*=\s*([A-Za-z0-9_,\s]+)")

# Tracing wrappers whose body runs at trace time: @jit / @shard_map / @pmap
# decorations (directly or through functools.partial) put the function body
# in a jit context for HVD106/HVD107.
_JIT_WRAPPER_NAMES = {"jit", "shard_map", "pmap"}

# ZeRO-sharded configuration arguments (ISSUE 15, HVD110): these shape the
# whole data plane (reduce-scatter + allgather vs allreduce, 1/N shard
# layouts) and ride the negotiation digest — they must be fleet-uniform,
# never derived from rank identity.  Checked on collective submissions and
# on the wrappers that accept them.  ``hierarchical`` (ISSUE 17) rides the
# fusion key rather than the digest, but batching groups entries BY fusion
# key, so a rank-divergent value still forks the batch plan — same rule.
# ``prefetch`` (ISSUE 18) is fusion-key-only too AND picks the dispatch
# lane, so divergence would also reorder the backlog per rank.
_SHARD_ARG_NAMES = {"sharded", "num_shards", "shard_count", "hierarchical",
                    "prefetch"}
_SHARD_ARG_CALLS = {"DistributedOptimizer", "sharded_optimizer",
                    "init_sharded_state", "full_sharded_optimizer",
                    "init_full_sharded_state"}


def _call_name(node: ast.AST) -> Optional[str]:
    """Last dotted segment of a call target: ``hvd.ops.allreduce`` → ``allreduce``."""
    if isinstance(node, ast.Call):
        node = node.func
    if isinstance(node, ast.Attribute):
        return node.attr
    if isinstance(node, ast.Name):
        return node.id
    return None


def _is_collective_call(node: ast.Call) -> bool:
    return _call_name(node) in COLLECTIVE_NAMES


def _mentions_rank(expr: ast.AST, tainted: Set[str]) -> bool:
    """True when the expression reads rank identity — a direct
    rank()/local_rank() call or a variable assigned from one."""
    for sub in ast.walk(expr):
        if isinstance(sub, ast.Call) and _call_name(sub) in _RANK_CALLS:
            return True
        if isinstance(sub, ast.Name) and sub.id in tainted:
            return True
    return False


def _iter_over_set_or_dict(it: ast.AST,
                           tainted: Optional[Set[str]] = None
                           ) -> Tuple[Optional[str], bool]:
    """Classify a for-loop iterable: ``(kind, neutralized)`` with kind
    'set'/'dict'/None.

    ``sorted(...)`` at the top neutralizes the ITERATION-order hazard —
    unless its ``key=`` is derived from rank identity, in which case each
    rank sorts into a different order and the hazard stands (ISSUE 16
    satellite: a sorted() wrapper must not launder rank-divergent order).
    """
    if isinstance(it, ast.Call) and _call_name(it) == "sorted":
        kind, _ = _iter_over_set_or_dict(it.args[0], tainted) if it.args \
            else (None, False)
        for kw in it.keywords:
            if kw.arg == "key" and tainted is not None \
                    and _mentions_rank(kw.value, tainted):
                return kind, False
        return kind, True
    if isinstance(it, (ast.Set, ast.SetComp)):
        return "set", False
    if isinstance(it, ast.Call):
        name = _call_name(it)
        if name == "set":
            return "set", False
        if name in ("keys", "values", "items"):
            return "dict", False
        if name in ("enumerate", "list", "tuple", "reversed"):
            return _iter_over_set_or_dict(it.args[0], tainted) if it.args \
                else (None, False)
    return None, False


# In-graph lax collectives that name a mesh axis (positionally or via
# axis_name=) — HVD112 checks the name against the binding mesh's axes.
_LAX_AXIS_CALLS = {
    "psum", "pmean", "pmax", "pmin", "all_gather", "all_to_all",
    "ppermute", "pshuffle", "axis_index", "psum_scatter",
}


def _axes_from_mesh_call(call: ast.Call) -> Optional[Tuple[str, ...]]:
    """Statically known axis names of a mesh-constructing call:
    ``make_mesh({"dp": 2, "tp": 4})`` → ("dp", "tp");
    ``Mesh(devs, ("dp", "tp"))`` → ("dp", "tp");
    ``process_set_mesh(ps, axis_name="x")`` → ("x",).  None when the axes
    are not literal (no check is possible — and no false positive)."""
    name = _call_name(call)
    if name == "make_mesh":
        cands = list(call.args) + [kw.value for kw in call.keywords
                                   if kw.arg == "axis_sizes"]
        for arg in cands:
            if isinstance(arg, ast.Dict):
                keys = tuple(k.value for k in arg.keys
                             if isinstance(k, ast.Constant)
                             and isinstance(k.value, str))
                if keys and len(keys) == len(arg.keys):
                    return keys
        return None
    if name == "Mesh":
        cands = list(call.args[1:2]) + [kw.value for kw in call.keywords
                                        if kw.arg == "axis_names"]
        for arg in cands:
            if isinstance(arg, (ast.Tuple, ast.List)):
                if arg.elts and all(isinstance(e, ast.Constant)
                                    and isinstance(e.value, str)
                                    for e in arg.elts):
                    return tuple(e.value for e in arg.elts)
            elif isinstance(arg, ast.Constant) and isinstance(arg.value,
                                                              str):
                return (arg.value,)
        return None
    if name == "process_set_mesh":
        for kw in call.keywords:
            if kw.arg == "axis_name" and isinstance(kw.value, ast.Constant) \
                    and isinstance(kw.value.value, str):
                return (kw.value.value,)
        if len(call.args) >= 2 and isinstance(call.args[1], ast.Constant) \
                and isinstance(call.args[1].value, str):
            return (call.args[1].value,)
    return None


def _mesh_axis_vars(tree: ast.AST) -> Dict[str, Tuple[str, ...]]:
    """Names assigned from a mesh constructor with literal axes."""
    out: Dict[str, Tuple[str, ...]] = {}
    for node in ast.walk(tree):
        if isinstance(node, ast.Assign) and len(node.targets) == 1 \
                and isinstance(node.targets[0], ast.Name) \
                and isinstance(node.value, ast.Call):
            axes = _axes_from_mesh_call(node.value)
            if axes:
                out[node.targets[0].id] = axes
    return out


def _mesh_axes_of_expr(expr: Optional[ast.AST],
                       mesh_vars: Dict[str, Tuple[str, ...]]
                       ) -> Optional[Tuple[str, ...]]:
    if isinstance(expr, ast.Name):
        return mesh_vars.get(expr.id)
    if isinstance(expr, ast.Call):
        return _axes_from_mesh_call(expr)
    return None


def _shard_map_call_info(node: ast.Call):
    """``(mesh_expr, spec_exprs, wrapped_name)`` for a ``shard_map(...)``
    call or a ``partial(shard_map, ...)`` decorator build; None otherwise."""
    name = _call_name(node)
    wrapped: Optional[ast.AST] = None
    if name == "shard_map":
        wrapped = node.args[0] if node.args else None
    elif not (name == "partial" and node.args
              and _call_name(node.args[0]) == "shard_map"):
        return None
    mesh = None
    specs: List[ast.AST] = []
    for kw in node.keywords:
        if kw.arg == "mesh":
            mesh = kw.value
        elif kw.arg in ("in_specs", "out_specs"):
            specs.append(kw.value)
    wname = wrapped.id if isinstance(wrapped, ast.Name) else None
    return mesh, specs, wname


def _jit_decorated(fn: ast.AST) -> bool:
    """True for ``@jax.jit`` / ``@jit`` / ``@partial(jax.jit, ...)`` /
    ``@functools.partial(shard_map, mesh=...)``-style decorations — any
    tracing wrapper in :data:`_JIT_WRAPPER_NAMES`, direct or via partial."""
    if not isinstance(fn, (ast.FunctionDef, ast.AsyncFunctionDef)):
        return False
    for dec in fn.decorator_list:
        name = _call_name(dec)
        if name in _JIT_WRAPPER_NAMES:
            return True
        if name == "partial" and isinstance(dec, ast.Call) and dec.args:
            if _call_name(dec.args[0]) in _JIT_WRAPPER_NAMES:
                return True
    return False


def unwrap_wrapped_callable(call: ast.AST) -> Optional[str]:
    """Peel tracing/functools wrappers off a call expression and return the
    underlying function NAME: ``jax.jit(step)`` → ``step``,
    ``jit(shard_map(step, mesh=m))`` → ``step``,
    ``functools.partial(helper, 3)`` → ``helper``.  Returns None when the
    innermost wrapped object is not a plain name (lambda, attribute chain)
    or the expression is not a recognized wrapper."""
    seen = False
    while isinstance(call, ast.Call) and \
            _call_name(call) in (_JIT_WRAPPER_NAMES | {"partial", "wraps"}):
        if _call_name(call) == "partial" and call.args and \
                _call_name(call.args[0]) in _JIT_WRAPPER_NAMES:
            # partial(jit, static_argnums=...) builds a DECORATOR, it does
            # not wrap a user function.
            return None
        seen = True
        call = call.args[0] if call.args else None
    if seen and isinstance(call, ast.Name):
        return call.id
    return None


def _jit_wrapped_fn_names(tree: ast.AST) -> Set[str]:
    """Names of locally defined functions wrapped in a tracing context by
    ASSIGNMENT rather than decoration: ``step = jax.jit(step_impl)`` (or
    ``jit(shard_map(step_impl, ...))``) puts ``step_impl``'s body in a jit
    context for HVD106/HVD107 even though ``step_impl`` itself carries no
    decorator — previously such bodies hid from the jit-context rules."""
    out: Set[str] = set()
    for node in ast.walk(tree):
        if isinstance(node, ast.Assign) and isinstance(node.value, ast.Call) \
                and _call_name(node.value) in _JIT_WRAPPER_NAMES:
            name = unwrap_wrapped_callable(node.value)
            if name:
                out.add(name)
    return out


class _FunctionFacts(ast.NodeVisitor):
    """Collect per-function taint: names assigned (transitively) from any
    of ``source_calls`` — rank-identity accessors by default; the
    whole-package HVD108 pass reuses this with world-size accessors to
    prove branch conditions rank-invariant."""

    def __init__(self, source_calls: Optional[Set[str]] = None):
        self.tainted: Set[str] = set()
        self._sources = _RANK_CALLS if source_calls is None else source_calls

    def visit_Assign(self, node: ast.Assign):
        self._track(node.targets, node.value)
        self.generic_visit(node)

    def visit_AnnAssign(self, node: ast.AnnAssign):
        if node.value is not None:
            self._track([node.target], node.value)
        self.generic_visit(node)

    def _track(self, targets, value):
        def taints(v) -> bool:
            return (isinstance(v, ast.Call)
                    and _call_name(v) in self._sources) or \
                   (isinstance(v, ast.Name) and v.id in self.tainted)

        vals: List[ast.AST]
        if isinstance(value, ast.Tuple):
            vals = list(value.elts)
        else:
            vals = [value]
        for tgt in targets:
            tgts = list(tgt.elts) if isinstance(tgt, ast.Tuple) else [tgt]
            if len(tgts) == len(vals):
                for t, v in zip(tgts, vals):
                    if isinstance(t, ast.Name) and taints(v):
                        self.tainted.add(t.id)
            elif len(tgts) == 1 and isinstance(tgts[0], ast.Name) \
                    and any(taints(v) for v in vals):
                self.tainted.add(tgts[0].id)


class _Linter(ast.NodeVisitor):
    def __init__(self, path: str, source: str):
        self.path = path
        self.findings: List[Finding] = []
        self.source = source
        # Module facts for HVD102/HVD103.
        self.has_init = False
        self.has_subgroup_sets = False
        self.has_sync = False
        self.has_training_wrapper = False
        self.uses_elastic_state = False
        self.init_line = 0
        self.first_training_line = 0
        self.collectives_without_ps: List[ast.Call] = []
        # Stack state while walking.
        self._fn_stack: List[dict] = []
        self._jit_depth = 0
        self._divergent_if_depth = 0
        # Per-function: line after which a rank-divergent early exit makes
        # later collectives subset-only.
        self._early_exit_after: List[Optional[int]] = []

    # -------------------------------------------------------------- helpers
    def _emit(self, rule: str, node: ast.AST, message: str):
        self.findings.append(Finding(
            rule=rule, path=self.path,
            line=getattr(node, "lineno", 1),
            col=getattr(node, "col_offset", 0) + 1,
            message=message))

    def _tainted(self) -> Set[str]:
        return self._fn_stack[-1]["tainted"] if self._fn_stack else \
            self._module_tainted

    # ------------------------------------------------------------ functions
    def visit_Module(self, node: ast.Module):
        facts = _FunctionFacts()
        facts.visit(node)
        self._module_tainted = facts.tainted
        self._jit_wrapped_names = _jit_wrapped_fn_names(node)
        # HVD112: mesh vars with literal axes, and functions put in a
        # shard_map context by ASSIGNMENT (``step = shard_map(impl,
        # mesh=m)`` / ``jit(shard_map(impl, mesh=m))``) — their bodies
        # bind exactly that mesh's axes.
        self._mesh_vars = _mesh_axis_vars(node)
        self._shard_axes_by_name: Dict[str, Tuple[str, ...]] = {}
        for sub in ast.walk(node):
            if isinstance(sub, ast.Call) and _call_name(sub) == "shard_map":
                info = _shard_map_call_info(sub)
                if info and info[0] is not None and info[2]:
                    axes = _mesh_axes_of_expr(info[0], self._mesh_vars)
                    if axes:
                        self._shard_axes_by_name[info[2]] = axes
        self.generic_visit(node)

    def _visit_function(self, node):
        facts = _FunctionFacts()
        facts.visit(node)
        # @hvd.elastic.run / @run (imported from horovod_tpu.elastic):
        # elastic-protected training syncs state on restore, which
        # satisfies HVD103's broadcast requirement.
        for dec in node.decorator_list:
            target = dec.func if isinstance(dec, ast.Call) else dec
            dotted = []
            n = target
            while isinstance(n, ast.Attribute):
                dotted.append(n.attr)
                n = n.value
            if isinstance(n, ast.Name):
                dotted.append(n.id)
            if dotted and dotted[0] == "run" and (
                    len(dotted) == 1 or "elastic" in dotted):
                self.uses_elastic_state = True
        jit = _jit_decorated(node) or \
            node.name in getattr(self, "_jit_wrapped_names", ())
        # HVD112 context: the mesh axes this function's body is
        # shard_map-bound to (decorator or assignment wrapping).
        shard_axes: Optional[Tuple[str, ...]] = None
        for dec in node.decorator_list:
            if isinstance(dec, ast.Call):
                info = _shard_map_call_info(dec)
                if info and info[0] is not None:
                    axes = _mesh_axes_of_expr(
                        info[0], getattr(self, "_mesh_vars", {}))
                    if axes:
                        shard_axes = axes
        if shard_axes is None:
            shard_axes = getattr(self, "_shard_axes_by_name",
                                 {}).get(node.name)
        self._fn_stack.append({"tainted": facts.tainted, "node": node,
                               "shard_axes": shard_axes})
        self._early_exit_after.append(None)
        if jit:
            self._jit_depth += 1
        self.generic_visit(node)
        if jit:
            self._jit_depth -= 1
        self._early_exit_after.pop()
        self._fn_stack.pop()

    visit_FunctionDef = _visit_function
    visit_AsyncFunctionDef = _visit_function

    # ------------------------------------------------- rank-divergent flow
    def _branch_has_exit(self, body: Sequence[ast.stmt]) -> bool:
        for stmt in body:
            for sub in ast.walk(stmt):
                if isinstance(sub, (ast.Return, ast.Raise, ast.Continue,
                                    ast.Break)):
                    return True
                if isinstance(sub, ast.Call) and _call_name(sub) in (
                        "exit", "_exit", "abort"):
                    return True
        return False

    def visit_If(self, node: ast.If):
        divergent = _mentions_rank(node.test, self._tainted())
        if divergent:
            self._divergent_if_depth += 1
        self.generic_visit(node)
        if divergent:
            self._divergent_if_depth -= 1
            if self._early_exit_after and self._early_exit_after[-1] is None \
                    and (self._branch_has_exit(node.body)
                         or (node.orelse
                             and self._branch_has_exit(node.orelse))):
                self._early_exit_after[-1] = node.end_lineno or node.lineno

    def visit_While(self, node: ast.While):
        divergent = _mentions_rank(node.test, self._tainted())
        if divergent:
            self._divergent_if_depth += 1
        self.generic_visit(node)
        if divergent:
            self._divergent_if_depth -= 1

    def visit_IfExp(self, node: ast.IfExp):
        divergent = _mentions_rank(node.test, self._tainted())
        if divergent:
            self._divergent_if_depth += 1
        self.generic_visit(node)
        if divergent:
            self._divergent_if_depth -= 1

    # ------------------------------------------------------------ for loops
    def visit_For(self, node: ast.For):
        kind, neutralized = _iter_over_set_or_dict(node.iter,
                                                   self._tainted())
        if kind is not None and not neutralized:
            for stmt in node.body:
                for sub in ast.walk(stmt):
                    if isinstance(sub, ast.Call) and _is_collective_call(sub):
                        rule = "HVD104" if kind == "set" else "HVD105"
                        self._emit(
                            rule, sub,
                            f"collective {_call_name(sub)!r} is submitted in "
                            f"{kind}-iteration order (loop at line "
                            f"{node.lineno}); ranks that build the {kind} "
                            f"differently submit in different order")
                        break
                else:
                    continue
                break
        elif kind is not None and neutralized:
            # sorted() fixed WHICH tensor comes out at each position — but
            # a grouped op whose process_set=/priorities= kwarg is derived
            # from rank identity still pairs each position with a
            # different communicator/priority per rank: same deadlock, a
            # sorted() wrapper must not launder it.
            done = False
            for stmt in node.body:
                if done:
                    break
                for sub in ast.walk(stmt):
                    if not (isinstance(sub, ast.Call)
                            and _is_collective_call(sub)):
                        continue
                    for kw in sub.keywords:
                        if kw.arg in ("process_set", "priorities") \
                                and _mentions_rank(kw.value,
                                                   self._tainted()):
                            rule = "HVD104" if kind == "set" else "HVD105"
                            self._emit(
                                rule, sub,
                                f"sorted() fixes the {kind}-iteration "
                                f"order of the loop at line {node.lineno}, "
                                f"but {kw.arg}= of "
                                f"{_call_name(sub)!r} is derived from "
                                f"rank identity — each rank still submits "
                                f"the group against a different process "
                                f"set/priority order")
                            done = True
                            break
                    if done:
                        break
        self.generic_visit(node)

    # ---------------------------------------------------------------- calls
    def visit_Call(self, node: ast.Call):
        name = _call_name(node)
        if name == "init":
            self.has_init = True
            self.init_line = self.init_line or node.lineno
        elif name == "add_process_set":
            self.has_subgroup_sets = True
        elif name in _SYNC_CALLS:
            self.has_sync = True
        elif name in ("JaxState", "TorchState", "TensorFlowKerasState"):
            # hvd.elastic state management syncs on restore.
            self.uses_elastic_state = True
        if name in _TRAINING_WRAPPERS:
            self.has_training_wrapper = True
            self.first_training_line = self.first_training_line or node.lineno

        if name in _HOST_SYNC_CALLS and self._jit_depth > 0:
            self._emit("HVD106", node,
                       f"{name!r} inside a jit-decorated function forces a "
                       f"host round-trip at trace/run time")

        self._check_axis_binding(node, name)

        if _is_collective_call(node):
            self._check_collective(node, name)
        if name in COLLECTIVE_NAMES or name in _SHARD_ARG_CALLS:
            self._check_shard_args(node, name)
        self.generic_visit(node)

    def _shard_axes(self) -> Optional[Tuple[str, ...]]:
        for entry in reversed(self._fn_stack):
            axes = entry.get("shard_axes")
            if axes is not None:
                return axes
        return None

    def _check_axis_binding(self, node: ast.Call, name: Optional[str]):
        """HVD112 (AST half): a collective naming an axis its binding mesh
        does not define, or a PartitionSpec naming an unknown axis at the
        shard_map site — the fsdp × tp mismatch.  Only fires when the
        mesh's axes are statically known (literal make_mesh/Mesh/
        process_set_mesh), so unknown meshes can't false-positive."""
        # (a) At a shard_map site with a known mesh: P()/PartitionSpec()
        # entries in in_specs/out_specs must name that mesh's axes.
        info = _shard_map_call_info(node) if isinstance(node, ast.Call) \
            else None
        if info and info[0] is not None:
            axes = _mesh_axes_of_expr(info[0],
                                      getattr(self, "_mesh_vars", {}))
            if axes:
                for spec in info[1]:
                    for sub in ast.walk(spec):
                        if isinstance(sub, ast.Call) and \
                                _call_name(sub) in ("P", "PartitionSpec"):
                            for c in ast.walk(sub):
                                if isinstance(c, ast.Constant) \
                                        and isinstance(c.value, str) \
                                        and c.value not in axes:
                                    self._emit(
                                        "HVD112", sub,
                                        f"PartitionSpec names axis "
                                        f"{c.value!r}, but the shard_map "
                                        f"mesh defines axes "
                                        f"{list(axes)} — the spec shards "
                                        f"over an axis that does not "
                                        f"exist on this mesh")
        # (b) Inside a shard_map-bound body: in-graph collectives must
        # name axes of THE binding mesh.
        axes = self._shard_axes()
        if axes is None:
            return
        if name not in _LAX_AXIS_CALLS and name not in COLLECTIVE_NAMES:
            return
        targets: List[ast.AST] = [kw.value for kw in node.keywords
                                  if kw.arg == "axis_name"]
        if not targets and name in _LAX_AXIS_CALLS and len(node.args) >= 2:
            targets = [node.args[1]]
        for t in targets:
            named: List[str] = []
            if isinstance(t, ast.Constant) and isinstance(t.value, str):
                named = [t.value]
            elif isinstance(t, (ast.Tuple, ast.List)):
                named = [e.value for e in t.elts
                         if isinstance(e, ast.Constant)
                         and isinstance(e.value, str)]
            for ax in named:
                if ax not in axes:
                    self._emit(
                        "HVD112", node,
                        f"collective {name!r} names axis {ax!r}, but its "
                        f"binding mesh defines axes {list(axes)} — the "
                        f"collective reduces over an axis that does not "
                        f"exist on this mesh (at best lowering fails; on "
                        f"a differently-built mesh it silently reduces "
                        f"over a 1-sized axis)")

    def _check_shard_args(self, node: ast.Call, name: str):
        """HVD110: sharded=/shard-count/hierarchical= arguments must be
        rank-invariant — sharded= is part of the negotiation digest and
        forks the whole collective schedule (reduce-scatter+allgather vs
        allreduce); hierarchical= is fusion-key-only but batching groups
        by fusion key, so divergence still forks the batch plan."""
        for kw in node.keywords:
            if kw.arg in _SHARD_ARG_NAMES \
                    and _mentions_rank(kw.value, self._tainted()):
                self._emit(
                    "HVD110", node,
                    f"{kw.arg}= argument of {name!r} is derived from rank "
                    f"identity: ranks would disagree on the collective "
                    f"data plane (sharded/two-level vs flat schedules) "
                    f"and submit mismatched programs")

    def _check_collective(self, node: ast.Call, name: str):
        if self._jit_depth > 0 and name in COLLECTIVE_NAMES \
                and not self._in_graph_spelling(node):
            self._emit("HVD107", node,
                       f"eager collective {name!r} inside a jit-decorated "
                       f"function submits to the engine at trace time")
        if self._divergent_if_depth > 0:
            self._emit("HVD101", node,
                       f"collective {name!r} is inside rank-divergent "
                       f"control flow: only a subset of ranks submits it, "
                       f"the rest of the world blocks in negotiation")
        elif self._early_exit_after and self._early_exit_after[-1] is not None \
                and node.lineno > self._early_exit_after[-1]:
            self._emit("HVD101", node,
                       f"collective {name!r} at line {node.lineno} is only "
                       f"reached by ranks that did not take the early "
                       f"return/raise under the rank-divergent branch ending "
                       f"at line {self._early_exit_after[-1]}")
        if not any(kw.arg == "process_set" for kw in node.keywords):
            self.collectives_without_ps.append(node)

    @staticmethod
    def _in_graph_spelling(node: ast.Call) -> bool:
        """In-graph collectives (``ops.collectives`` riding lax.psum) are
        jit-safe.  Recognized by an explicit ``axis_name=`` kwarg, or by the
        conventional receiver names for that module (``C.allreduce(x)``
        relying on the DEFAULT_AXIS default is correct in-graph code and
        must not fire HVD107)."""
        if any(kw.arg == "axis_name" for kw in node.keywords):
            return True
        func = node.func
        if isinstance(func, ast.Attribute) and isinstance(func.value,
                                                          ast.Name):
            return func.value.id in ("C", "collectives")
        return False

    # ------------------------------------------------------------ wrap-up
    def finish(self):
        if self.has_subgroup_sets:
            for node in self.collectives_without_ps:
                self._emit(
                    "HVD102", node,
                    f"collective {_call_name(node)!r} omits process_set= in "
                    f"a module that registers subgroup process sets; it "
                    f"targets the GLOBAL set — a deadlock if only subgroup "
                    f"members reach this call")
        if (self.has_init and self.has_training_wrapper
                and not self.has_sync and not self.uses_elastic_state):
            self.findings.append(Finding(
                rule="HVD103", path=self.path,
                line=self.first_training_line or self.init_line, col=1,
                message="training script calls init() and reduces gradients "
                        "but never broadcasts initial state from rank 0; "
                        "ranks train divergent models"))


def _suppressed_lines(source: str) -> Dict[int, Set[str]]:
    """Map line → suppressed rule IDs from ``# hvd-lint: disable=...``."""
    out: Dict[int, Set[str]] = {}
    try:
        toks = tokenize.generate_tokens(io.StringIO(source).readline)
        for tok in toks:
            if tok.type == tokenize.COMMENT:
                m = _DISABLE_RE.search(tok.string)
                if m:
                    ids = {s.strip().upper() for s in m.group(1).split(",")}
                    out.setdefault(tok.start[0], set()).update(ids)
    except (tokenize.TokenError, SyntaxError):  # pragma: no cover
        pass
    return out


def lint_source(source: str, path: str = "<string>") -> List[Finding]:
    """Lint one module's source; returns findings sorted by line."""
    try:
        tree = ast.parse(source, filename=path)
    except SyntaxError as e:
        return [Finding(rule="HVD100", path=path, line=e.lineno or 1,
                        col=(e.offset or 0) + 1,
                        message=f"syntax error: {e.msg}",
                        severity=Severity.ERROR,
                        fix_hint="fix the syntax error; the linter cannot "
                                 "analyze this file")]
    linter = _Linter(path, source)
    linter.visit(tree)
    linter.finish()
    suppressed = _suppressed_lines(source)
    out = []
    for f in linter.findings:
        ids = suppressed.get(f.line, set())
        if "ALL" in ids or f.rule in ids:
            continue
        out.append(f)
    return sorted(out, key=lambda f: (f.line, f.col, f.rule))


def lint_file(path: str) -> List[Finding]:
    with open(path, "r", encoding="utf-8") as fh:
        return lint_source(fh.read(), path)


def iter_python_files(paths: Iterable[str]) -> List[str]:
    """Expand files/dirs to lintable files.  Directories contribute their
    ``.py`` trees; an explicitly named file is linted regardless of suffix
    (a suffix-less training script is still Python); a missing path raises
    so the CLI can report a usage error instead of a clean verdict."""
    out: List[str] = []
    for p in paths:
        if os.path.isdir(p):
            for root, dirs, files in os.walk(p):
                dirs[:] = sorted(d for d in dirs
                                 if d not in ("__pycache__", ".git"))
                out.extend(os.path.join(root, f) for f in sorted(files)
                           if f.endswith(".py"))
        elif os.path.isfile(p):
            out.append(p)
        else:
            raise FileNotFoundError(f"no such file or directory: {p}")
    return out


def lint_paths(paths: Iterable[str]) -> List[Finding]:
    """Lint every ``.py`` file under the given files/directories."""
    findings: List[Finding] = []
    for f in iter_python_files(paths):
        findings.extend(lint_file(f))
    return findings
