"""Jaxpr-level collective checker (rules HVD201–HVD203).

The static analogue of the controller's negotiation in
``common/controller.py``: trace a step function (under its real mesh or an
abstract stand-in) and build a **collective ledger** — the ordered sequence
of (primitive, axes, shape, dtype) every rank will execute.  Because SPMD
traces once for all ranks, the ledger is consistent by construction; what
can still go wrong statically is checked here:

- HVD201: a collective names an ``axis_name`` no enclosing mesh binds;
- HVD202: ``axis_index_groups`` that do not partition the axis;
- HVD203: host-callback primitives buried in the traced step;
- HVD204: a ``ppermute`` whose perm is not a bijection over the axis
  (non-bijective perms deadlock on multi-host exactly like bad
  ``axis_index_groups`` — JAX's zero-fill semantics mask it locally);
- HVD112: when the caller declares which axes its partition specs
  actually shard over (``partition_axes=``), a collective over a *bound
  but undeclared* axis is the fsdp × tp mismatch — the reduction runs
  over an axis the data is not partitioned on, silently reducing
  replicated values.  (HVD201 stays the unbound-axis case; HVD112 is
  the bound-but-mismatched case, mirroring the AST check in
  ``collective_lint``.)

``compare_ledgers`` diffs two ledgers (e.g. a refactored step against the
golden one, or per-process ledgers recorded by the runtime sanitizer) and
names the first divergence.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Dict, List, Optional, Sequence, Tuple

from .findings import Finding

# Primitive names that move data across mesh axes.
COLLECTIVE_PRIMITIVES = {
    "psum", "psum2", "pmin", "pmax", "ppermute", "pbroadcast",
    "all_gather", "all_to_all", "reduce_scatter", "psum_scatter",
}
# Reads rank identity; tracked in the ledger (order matters for fusion) but
# moves no bytes.
INDEX_PRIMITIVES = {"axis_index"}
# Host-callback primitives (HVD203).
CALLBACK_PRIMITIVES = {
    "pure_callback", "io_callback", "debug_callback", "outside_call",
    "host_callback_call",
}


@dataclasses.dataclass(frozen=True)
class CollectiveRecord:
    """One traced collective: the static twin of the controller digest."""
    index: int
    primitive: str
    axes: Tuple[str, ...]
    shapes: Tuple[Tuple[int, ...], ...]
    dtypes: Tuple[str, ...]
    axis_index_groups: Optional[Tuple[Tuple[int, ...], ...]] = None

    def digest(self) -> str:
        """Signature string, comparable across ranks/versions — the same
        role the controller's ``_digest`` plays on the wire."""
        return "|".join([self.primitive, ",".join(self.axes),
                         str(self.shapes), str(self.dtypes),
                         str(self.axis_index_groups)])


@dataclasses.dataclass
class TraceReport:
    ledger: List[CollectiveRecord]
    findings: List[Finding]
    bound_axes: Dict[str, int]

    @property
    def ok(self) -> bool:
        return not any(f.is_error for f in self.findings)


def _normalize_axes(val: Any) -> Tuple[str, ...]:
    if val is None:
        return ()
    if isinstance(val, (tuple, list)):
        return tuple(str(a) for a in val if isinstance(a, (str,)) or a)
    return (str(val),)


def _named_axes(val: Any) -> Tuple[str, ...]:
    """Keep only *named* axes: psum over positional ints (vmapped axes)
    moves nothing across the mesh."""
    if val is None:
        return ()
    vals = val if isinstance(val, (tuple, list)) else [val]
    return tuple(a for a in vals if isinstance(a, str))


def _sub_jaxprs(params: Dict[str, Any]):
    """Yield (jaxpr, extra_bound_axes) for every sub-jaxpr in an eqn's
    params — pjit/closed_call carry ClosedJaxprs, scan/while/cond carry them
    in lists, shard_map carries its mesh (which binds new axes)."""
    extra: Dict[str, int] = {}
    mesh = params.get("mesh")
    if mesh is not None and hasattr(mesh, "shape"):
        try:
            extra = dict(mesh.shape)
        except Exception:  # pragma: no cover - exotic mesh types
            extra = {}
    axis_name = params.get("axis_name")
    if axis_name is not None and "global_axis_size" in params:  # pmap
        for a in _normalize_axes(axis_name):
            extra[a] = params.get("global_axis_size") or 0
    for v in params.values():
        items = v if isinstance(v, (tuple, list)) else [v]
        for item in items:
            if hasattr(item, "eqns"):                      # raw Jaxpr
                yield item, extra
            elif hasattr(item, "jaxpr") and hasattr(item.jaxpr, "eqns"):
                yield item.jaxpr, extra                    # ClosedJaxpr


def _check_ppermute(rec: CollectiveRecord, perm, bound: Dict[str, int],
                    findings: List[Finding], path: str):
    """HVD204: a ppermute's perm must be a bijection over its axis —
    every rank appears exactly once as a source and once as a destination,
    all within [0, axis_size).  Non-bijective perms deadlock on multi-host
    runtimes the way bad axis_index_groups do (HVD202); JAX's local
    zero-fill semantics hide the bug until the pod launch."""
    if perm is None or not rec.axes:
        return
    # ppermute over several named axes indexes ranks over the axes'
    # flattened PRODUCT — validating against axes[0] alone would flag
    # valid rings on multi-axis meshes.
    sizes = [bound.get(a) for a in rec.axes]
    if any(not s for s in sizes):
        return
    size = 1
    for s in sizes:
        size *= s
    ax = rec.axes[0] if len(rec.axes) == 1 else tuple(rec.axes)
    pairs = [tuple(p) for p in perm]
    srcs = [p[0] for p in pairs]
    dsts = [p[1] for p in pairs]

    def _fail(detail: str, severity=None):
        findings.append(Finding(
            rule="HVD204", path=path, line=rec.index, col=1,
            severity=severity,
            message=f"collective #{rec.index} (ppermute) over axis {ax!r} "
                    f"of size {size} is not a bijection: {detail}"))

    oob = sorted({r for r in srcs + dsts if r < 0 or r >= size})
    if oob:
        _fail(f"ranks {oob} are outside [0, {size})")
        return
    dup_src = sorted({s for s in srcs if srcs.count(s) > 1})
    dup_dst = sorted({d for d in dsts if dsts.count(d) > 1})
    if dup_src or dup_dst:
        detail = []
        if dup_src:
            detail.append(f"sources {dup_src} send more than once")
        if dup_dst:
            detail.append(f"destinations {dup_dst} receive more than once")
        _fail("; ".join(detail))
        return
    missing = sorted(set(range(size)) - set(srcs))
    if missing:
        # WARNING, not error: partial perms are defined JAX semantics
        # (uncovered destinations read zeros) and XLA's CollectivePermute
        # accepts them — but they are the classic accident behind
        # wedge-shaped halo/pipeline bugs, and point-to-point emulations
        # over eager runtimes deadlock on them, so they stay flagged.
        from .findings import Severity
        _fail(f"ranks {missing} appear in no (src, dst) pair (valid "
              f"zero-fill semantics under XLA, but deadlock-prone on "
              f"point-to-point runtimes; make the ring explicit if the "
              f"gap is intended)", severity=Severity.WARNING)


def _walk(jaxpr, bound: Dict[str, int], ledger: List[CollectiveRecord],
          findings: List[Finding], path: str,
          declared: Optional[frozenset] = None):
    for eqn in jaxpr.eqns:
        name = eqn.primitive.name
        params = eqn.params
        if name in COLLECTIVE_PRIMITIVES or name in INDEX_PRIMITIVES:
            axes = _named_axes(params.get("axes",
                                          params.get("axis_name")))
            shapes = tuple(tuple(getattr(v.aval, "shape", ()))
                           for v in eqn.invars if hasattr(v, "aval"))
            dtypes = tuple(str(getattr(v.aval, "dtype", "?"))
                           for v in eqn.invars if hasattr(v, "aval"))
            groups = params.get("axis_index_groups")
            groups_t = tuple(tuple(g) for g in groups) if groups else None
            rec = CollectiveRecord(index=len(ledger), primitive=name,
                                   axes=axes, shapes=shapes, dtypes=dtypes,
                                   axis_index_groups=groups_t)
            ledger.append(rec)
            for ax in axes:
                if ax not in bound:
                    findings.append(Finding(
                        rule="HVD201", path=path, line=rec.index, col=1,
                        message=f"collective #{rec.index} ({name}) reduces "
                                f"over axis {ax!r}, but the mesh only binds "
                                f"axes {sorted(bound)} — this fails at "
                                f"lowering or silently no-ops"))
                elif declared is not None and ax not in declared \
                        and name in COLLECTIVE_PRIMITIVES:
                    # axis_index over an undeclared axis is fine (rng
                    # folding); only data-moving collectives reduce
                    # replicated values.
                    findings.append(Finding(
                        rule="HVD112", path=path, line=rec.index, col=1,
                        message=f"collective #{rec.index} ({name}) reduces "
                                f"over axis {ax!r}, which the mesh binds but "
                                f"the step's partition specs never shard "
                                f"over (declared: {sorted(declared)}) — the "
                                f"reduction runs over replicated data, "
                                f"scaling results by the axis size"))
            if groups_t is not None and axes:
                ax = axes[0]
                size = bound.get(ax)
                if size:
                    flat = [r for g in groups_t for r in g]
                    if sorted(flat) != list(range(size)):
                        findings.append(Finding(
                            rule="HVD202", path=path, line=rec.index, col=1,
                            message=f"collective #{rec.index} ({name}) has "
                                    f"axis_index_groups {groups_t} which do "
                                    f"not partition axis {ax!r} of size "
                                    f"{size}: ranks left out of every group "
                                    f"wait forever"))
            if name == "ppermute":
                _check_ppermute(rec, params.get("perm"), bound, findings,
                                path)
        elif name in CALLBACK_PRIMITIVES:
            findings.append(Finding(
                rule="HVD203", path=path, line=len(ledger), col=1,
                message=f"host callback primitive {name!r} inside the "
                        f"traced step (after collective #{len(ledger) - 1})"))
        for sub, extra in _sub_jaxprs(params):
            inner = dict(bound)
            inner.update(extra)
            _walk(sub, inner, ledger, findings, path, declared)


def check_step_fn(fn, *example_args, mesh=None,
                  axis_sizes: Optional[Dict[str, int]] = None,
                  partition_axes: Optional[Sequence[str]] = None,
                  path: str = "<trace>") -> TraceReport:
    """Trace ``fn(*example_args)`` and audit its collective ledger.

    ``mesh``: the Mesh the step runs under (optional if fn contains its own
    shard_map, whose mesh binds the axes).  ``axis_sizes``: extra name→size
    bindings, for step fns written to run under an outer pmap/shard_map
    supplied elsewhere.  ``partition_axes``: the axes the step's partition
    specs actually shard over; when given, a collective over a bound axis
    *outside* this set fires HVD112 (the fsdp × tp mismatch — reducing
    replicated data).  Example args may be arrays or ShapeDtypeStructs —
    tracing is abstract, nothing executes.
    """
    import jax

    bound: Dict[str, int] = {}
    if mesh is not None and hasattr(mesh, "shape"):
        bound.update(dict(mesh.shape))
    if axis_sizes:
        bound.update(axis_sizes)

    findings: List[Finding] = []
    # Only the explicitly-requested outer bindings go into the trace's
    # axis_env: mesh axes are bound by the step's own shard_map — binding
    # them twice would shadow/collide.
    axis_env = list(axis_sizes.items()) if axis_sizes else None
    try:
        closed = jax.make_jaxpr(fn, axis_env=axis_env)(*example_args)
    except NameError as e:
        # lax collectives raise NameError("unbound axis name: ...") at
        # trace time — the step names an axis neither the mesh nor any
        # inner shard_map binds.
        findings.append(Finding(
            rule="HVD201", path=path, line=0, col=1,
            message=f"step references an axis no mesh binds "
                    f"(bound: {sorted(bound)}): {e}"))
        return TraceReport(ledger=[], findings=findings, bound_axes=bound)
    except Exception as e:  # surface trace failures as findings, not crashes
        findings.append(Finding(
            rule="HVD201", path=path, line=0, col=1,
            message=f"step function failed to trace: {type(e).__name__}: "
                    f"{e}"))
        return TraceReport(ledger=[], findings=findings, bound_axes=bound)

    ledger: List[CollectiveRecord] = []
    declared = frozenset(partition_axes) if partition_axes is not None \
        else None
    _walk(closed.jaxpr, bound, ledger, findings, path, declared)
    return TraceReport(ledger=ledger, findings=findings, bound_axes=bound)


def compare_ledgers(a: Sequence[CollectiveRecord],
                    b: Sequence[CollectiveRecord],
                    names: Tuple[str, str] = ("rank A", "rank B"),
                    path: str = "<ledger>") -> List[Finding]:
    """Diff two collective ledgers; findings name the first divergence.

    The offline twin of the controller's per-tensor digest mismatch check:
    run it over ledgers recorded by the runtime sanitizer, or over two
    traced variants of a step that must stay wire-compatible.
    """
    findings: List[Finding] = []
    for i, (ra, rb) in enumerate(zip(a, b)):
        if ra.digest() != rb.digest():
            findings.append(Finding(
                rule="HVD301", path=path, line=i, col=1,
                message=f"ledgers diverge at collective #{i}: "
                        f"{names[0]} submitted {ra.digest()} but "
                        f"{names[1]} submitted {rb.digest()}"))
            break
    else:
        if len(a) != len(b):
            longer, shorter = (names[0], names[1]) if len(a) > len(b) \
                else (names[1], names[0])
            extra = (a if len(a) > len(b) else b)[min(len(a), len(b))]
            findings.append(Finding(
                rule="HVD301", path=path, line=min(len(a), len(b)), col=1,
                message=f"{longer} submitted {abs(len(a) - len(b))} more "
                        f"collective(s) than {shorter}, starting with "
                        f"{extra.digest()} — {shorter} will block forever"))
    return findings
