"""The ``check=`` hook shared by the framework bindings.

``DistributedOptimizer(..., check=True)`` (torch, tensorflow and jax
bindings alike) lints the *calling script* at wrap time — the moment every
Horovod training script passes through — and reports deadlock-prone
collective patterns before the first step runs:

- ``check=False`` (default): no analysis.
- ``check=True`` / ``check="warn"``: log findings as warnings.
- ``check="strict"``: additionally raise :class:`CollectiveCheckError`
  when any error-severity finding is present.
"""

from __future__ import annotations

import inspect
import os
from typing import List, Optional

from .collective_lint import lint_file
from .findings import Finding, is_package_frame, summarize
from ..utils.logging import get_logger

log = get_logger()


class CollectiveCheckError(RuntimeError):
    """Raised by ``check='strict'`` when the caller's script has
    error-severity collective findings."""

    def __init__(self, findings: List[Finding]):
        self.findings = findings
        msgs = "\n".join(f.render() for f in findings)
        super().__init__(
            f"collective-correctness check failed "
            f"({summarize(findings)}):\n{msgs}")


def _caller_file(depth: int = 2) -> Optional[str]:
    """Source file of the user frame ``depth`` levels up (skipping this
    package's own frames — ``findings.is_package_frame`` decides what
    counts as package code)."""
    frame = inspect.currentframe()
    try:
        for _ in range(depth):
            if frame is None:
                return None
            frame = frame.f_back
        while frame is not None:
            fn = frame.f_code.co_filename
            if not is_package_frame(fn) and os.path.isfile(fn):
                return fn
            frame = frame.f_back
        return None
    finally:
        del frame


def run_check_hook(check, caller_file: Optional[str] = None
                   ) -> List[Finding]:
    """Execute the ``check=`` contract; returns the findings (possibly
    empty).  ``check`` falsy → no-op."""
    if not check:
        return []
    path = caller_file or _caller_file(depth=3)
    if path is None:
        log.warning("check=%r: could not locate the calling script to lint",
                    check)
        return []
    findings = lint_file(path)
    for f in findings:
        log.warning("%s", f.render())
    errors = [f for f in findings if f.is_error]
    if check == "strict" and errors:
        raise CollectiveCheckError(errors)
    return findings
