"""Pass 1 of the whole-package analyzer: symbol table + call graph.

Per-module lint (``collective_lint``) cannot see across function or module
boundaries: a ``psum`` inside a helper called from a rank-guarded branch is
invisible to HVD101, and HVD102/HVD103 facts (process-set registration,
initial-broadcast hygiene) don't flow between modules.  This module walks
every file of a package ONCE and builds the structures pass 2
(:mod:`.whole_package`) propagates facts over:

- a **symbol table** per module: top-level functions, classes with their
  methods and base classes, import aliases (``import a.b as c``,
  ``from .m import f`` — relative imports resolved against the module's
  package), and callable aliases through wrapper factories
  (``step = jax.jit(train_step)``, ``g = functools.partial(helper, 3)``);
- a **call graph**: every call site, annotated with the rank-guard context
  it sits in (inside an ``if rank() == 0:`` branch, or after a
  rank-divergent early return) and resolved best-effort to the defining
  :class:`FunctionNode` — including method resolution for the
  optimizer/tape binding idiom (``opt = hvd.DistributedOptimizer(...);
  opt.apply_gradients(...)`` and ``self.attr = C(...); self.attr.m()``);
- per-function **fact summaries** (collective sites, init/broadcast/
  process-set calls) that pass 2 unions over entry-point closures.

Known imprecision (documented in docs/analysis.md): dynamic dispatch
through containers, ``getattr`` calls, and functions passed as values are
not resolved; decorators are treated as transparent (the decorated body is
assumed reachable through the name).  Everything here is pure ``ast`` —
no jax import, nothing executes.
"""

from __future__ import annotations

import ast
import dataclasses
import os
from typing import Any, Dict, Iterable, List, Optional, Sequence, Set, Tuple

from .collective_lint import (
    COLLECTIVE_NAMES, _FunctionFacts, _TRAINING_WRAPPERS, _call_name,
    _mentions_rank, _suppressed_lines, iter_python_files,
    unwrap_wrapped_callable,
)

# Elastic/churn handlers that run while the rank set is MID-TRANSITION
# (HVD109).  ``on_reset`` is deliberately absent: reference semantics run
# reset callbacks AFTER re-rendezvous completes, where a state-sync
# broadcast is the sanctioned pattern.
MID_TRANSITION_CALLBACKS = {
    "on_leave", "on_join", "new_generation", "end_generation",
    "on_hosts_updated", "on_preempt", "on_host_down", "on_host_added",
    "on_drain",
}

_UNIFORM_CALLS = {
    # Rank-INVARIANT reads: every rank computes the same value, so a branch
    # on them does not diverge the collective schedule (HVD108 exemption).
    "size", "local_size", "cross_size", "num_ranks", "world_size",
    "device_count", "local_device_count", "process_count",
    "is_initialized", "initialized",
}


@dataclasses.dataclass(frozen=True)
class ProcessSetValue:
    """Abstract value of a collective's process-set argument (the dataflow
    domain HVD111/113/114 run over).

    ``kind`` is one of:

    - ``"world"``   — no ``process_set=`` (or an explicit ``None``): the
      global set, id 0;
    - ``"named"``   — a value traced to an ``add_process_set(...)`` /
      ``ProcessSet(...)`` registration; ``ranks`` carries the literal rank
      list when the registration spelled one;
    - ``"param"``   — the enclosing function's own ``process_set``-style
      parameter (a scoped helper, resolved per call site by pass 2);
    - ``"unknown"`` — anything the tracker cannot prove.

    Overlap judgements (:func:`proven_overlap`) are deliberately
    one-sided: only PROVEN overlap fires the ERROR rules, so an unknown
    value can never produce a false HVD111.
    """
    kind: str
    spelling: str
    ranks: Optional[Tuple[int, ...]] = None

    @property
    def lane(self) -> str:
        """Stable per-set schedule-lane key (world is the default lane)."""
        if self.kind == "world":
            return "world"
        if self.kind == "param":
            return f"<{self.spelling}>"
        if self.kind == "unknown":
            return f"?{self.spelling}"
        return self.spelling

    def describe(self) -> str:
        if self.kind == "world":
            return "the world set"
        if self.kind == "named" and self.ranks is not None:
            return f"process set {self.spelling} (ranks {list(self.ranks)})"
        if self.kind == "param":
            return f"the caller-supplied process set '{self.spelling}'"
        return f"process set {self.spelling}"


WORLD = ProcessSetValue("world", "<world>")


def proven_overlap(a: ProcessSetValue, b: ProcessSetValue) -> bool:
    """True only when two DISTINCT sets provably share at least one rank.

    Every registered set is a nonempty subset of the world, so
    (world, named) always overlaps; two named sets overlap only when both
    spelled literal rank lists that intersect.  params/unknowns never
    prove overlap — the conservative side that keeps HVD111 free of false
    positives on disjoint or unresolvable sets.
    """
    if a.lane == b.lane:
        return False                 # same lane: one stream, no entangling
    kinds = (a.kind, b.kind)
    if "world" in kinds:
        other = b if a.kind == "world" else a
        return other.kind == "named"
    if a.kind == "named" and b.kind == "named" \
            and a.ranks is not None and b.ranks is not None:
        return bool(set(a.ranks) & set(b.ranks))
    return False


def _literal_ranks(call: ast.Call) -> Optional[Tuple[int, ...]]:
    """``add_process_set([0, 2])`` → ``(0, 2)``; None when not literal."""
    args = list(call.args)
    for kw in call.keywords:
        if kw.arg in ("ranks", "ps_or_ranks"):
            args.append(kw.value)
    for arg in args:
        if isinstance(arg, (ast.List, ast.Tuple)):
            vals = []
            for e in arg.elts:
                if isinstance(e, ast.Constant) and isinstance(e.value, int):
                    vals.append(e.value)
                else:
                    return None
            return tuple(sorted(vals))
    return None


@dataclasses.dataclass(frozen=True)
class Guard:
    """A rank-divergent context a call site sits in."""
    line: int
    kind: str            # "branch" | "early-exit"

    def describe(self, module_base: str) -> str:
        what = "rank-guarded branch" if self.kind == "branch" else \
            "rank-divergent early exit"
        return f"{what} at {module_base}:{self.line}"


@dataclasses.dataclass
class CallSite:
    callee_expr: Optional[str]   # dotted spelling as written, None if exotic
    line: int
    col: int
    guard: Optional[Guard]
    resolved: Optional["FunctionNode"] = None
    # Resolved ``process_set=`` kwarg at this call site — explicit, or
    # pinned by a ``functools.partial(helper, process_set=...)`` alias the
    # call goes through.  Pass 2 substitutes it for the callee's ``param``
    # values (HVD113's scoped-region entry edges).
    ps_kwarg: Optional[ProcessSetValue] = None


@dataclasses.dataclass
class CollectiveSite:
    name: str
    line: int
    col: int
    guard: Optional[Guard]
    has_process_set: bool
    # ZeRO-sharded site (ISSUE 15/18): the constant ``sharded=`` value a
    # collective was submitted with (True or "full"), or the mode of the
    # synthetic ``sharded_update`` site registered for ``opt.update(...)``
    # on a DistributedOptimizer(sharded=...) / sharded_optimizer /
    # full_sharded_optimizer binding — the schedule pass expands the
    # latter to its real reduce-scatter + allgather sequence, tagged
    # [sharded] or [full] by mode.
    sharded: Any = False
    # Two-level dispatch pin (ISSUE 17): a collective submitted with a
    # constant hierarchical= override.  Unlike sharded= it rides the
    # fusion key only (never the negotiation digest), but it still forks
    # the batch plan — the schedule pass keys on it like [sharded].
    hierarchical: bool = False
    # Resolved process-set value of this site (the schedule lane it
    # submits on); WORLD when no process_set= / axis binding applies.
    ps: ProcessSetValue = WORLD


@dataclasses.dataclass
class FunctionNode:
    qname: str                   # "modname:Class.method" / "modname:<module>"
    module: "ModuleInfo"
    name: str
    cls: Optional[str]
    lineno: int
    node: Optional[ast.AST]
    calls: List[CallSite] = dataclasses.field(default_factory=list)
    collectives: List[CollectiveSite] = dataclasses.field(
        default_factory=list)
    called_names: Set[str] = dataclasses.field(default_factory=set)
    # var -> ("instance"|"alias", dotted target expr)
    bindings: Dict[str, Tuple[str, str]] = dataclasses.field(
        default_factory=dict)
    uses_elastic_state: bool = False
    is_callback: bool = False
    in_edges: int = 0
    # Names bound to a sharded optimizer wrapper in this scope, mapped to
    # the sharding mode (True = ZeRO-1, "full" = ZeRO-3/FSDP): their
    # ``.update()`` calls register synthetic sharded_update sites.
    sharded_opt_vars: Dict[str, Any] = dataclasses.field(
        default_factory=dict)
    # Process-set dataflow (ISSUE 16): parameter names (so a
    # ``process_set=<param>`` resolves to kind="param"), names bound to
    # registered sets in this scope, partial-pinned process_set kwargs
    # (var of the partial alias -> pinned value), and mesh-axis bindings
    # from ``process_set_mesh(ps, axis_name=...)``.
    params: Tuple[str, ...] = ()
    ps_bindings: Dict[str, ProcessSetValue] = dataclasses.field(
        default_factory=dict)
    partial_ps: Dict[str, ProcessSetValue] = dataclasses.field(
        default_factory=dict)
    axis_bindings: Dict[str, ProcessSetValue] = dataclasses.field(
        default_factory=dict)

    @property
    def short(self) -> str:
        return f"{os.path.basename(self.module.path)}:{self.lineno} " \
               f"({self.name if not self.cls else self.cls + '.' + self.name})"


@dataclasses.dataclass
class ClassInfo:
    name: str
    qname: str
    module: "ModuleInfo"
    bases: List[str]
    methods: Dict[str, FunctionNode] = dataclasses.field(default_factory=dict)
    attr_types: Dict[str, str] = dataclasses.field(default_factory=dict)


@dataclasses.dataclass
class ModuleInfo:
    path: str
    modname: str
    package: str                 # package relative imports resolve against
    source: str = ""             # kept so pass 2 lints without re-reading
    functions: Dict[str, FunctionNode] = dataclasses.field(
        default_factory=dict)          # top-level (and nested) defs by name
    classes: Dict[str, ClassInfo] = dataclasses.field(default_factory=dict)
    imports: Dict[str, str] = dataclasses.field(default_factory=dict)
    toplevel: Optional[FunctionNode] = None
    all_functions: List[FunctionNode] = dataclasses.field(
        default_factory=list)
    suppressed: Dict[int, Set[str]] = dataclasses.field(default_factory=dict)
    init_line: int = 0
    first_training_line: int = 0

    @property
    def base(self) -> str:
        return os.path.basename(self.path)


@dataclasses.dataclass
class Package:
    # For IMPORT RESOLUTION, keyed by dotted module name (first wins on a
    # stem collision — two unrelated dir1/train.py + dir2/train.py can't
    # import each other anyway).
    modules: Dict[str, ModuleInfo]
    functions: Dict[str, FunctionNode]     # by qname (resolution only)
    classes: Dict[str, ClassInfo]          # by "modname:Class"
    # EVERY analyzed module, collisions included: the analysis passes
    # (closures, facts, schedules, findings) iterate this, so a shadowed
    # modname never silently drops a file's findings.
    all_modules: List[ModuleInfo] = dataclasses.field(default_factory=list)

    def iter_functions(self) -> Iterable[FunctionNode]:
        for mod in self.all_modules:
            for fn in mod.all_functions:
                yield fn


def module_name_for(path: str) -> str:
    """Dotted module name: ascend while ``__init__.py`` marks a package."""
    path = os.path.abspath(path)
    stem = os.path.splitext(os.path.basename(path))[0]
    parts = [] if stem == "__init__" else [stem]
    d = os.path.dirname(path)
    while os.path.isfile(os.path.join(d, "__init__.py")):
        parts.insert(0, os.path.basename(d))
        d = os.path.dirname(d)
    return ".".join(parts) or stem


def _dotted(node: ast.AST) -> Optional[str]:
    """Render an attribute chain to a dotted string; None for exotica."""
    parts: List[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return None


def _resolve_relative(package: str, level: int, module: Optional[str]) -> str:
    """``from ..a import b`` in package ``p.q`` → base ``p.a``."""
    parts = package.split(".") if package else []
    if level > 1:
        parts = parts[: len(parts) - (level - 1)]
    if module:
        parts = parts + module.split(".")
    return ".".join(parts)


class _Collector(ast.NodeVisitor):
    """One pass over a module: symbols, guarded call sites, fact summaries.

    Mirrors the per-module linter's rank-guard model (_mentions_rank taint,
    divergent-if depth, early-exit lines) so whole-package HVD101 findings
    agree with per-function ones about what counts as guarded.
    """

    def __init__(self, mod: ModuleInfo, tree: ast.Module):
        self.mod = mod
        self._fn_stack: List[FunctionNode] = []
        self._guard_stack: List[Guard] = []
        self._early_exit: List[Optional[Guard]] = []
        self._class_stack: List[ClassInfo] = []
        facts = _FunctionFacts()
        facts.visit(tree)
        self._taint_stack: List[Set[str]] = [facts.tainted]
        top = FunctionNode(qname=f"{mod.modname}:<module>", module=mod,
                           name="<module>", cls=None, lineno=1, node=tree)
        mod.toplevel = top
        mod.all_functions.append(top)
        self._fn_stack.append(top)
        self._early_exit.append(None)

    # ----------------------------------------------------------- helpers
    def _cur(self) -> FunctionNode:
        return self._fn_stack[-1]

    def _cur_guard(self) -> Optional[Guard]:
        if self._guard_stack:
            return self._guard_stack[-1]
        return self._early_exit[-1]

    # --------------------------------------------------------- functions
    def _visit_function(self, node):
        cls = self._class_stack[-1] if self._class_stack else None
        qname = f"{self.mod.modname}:" + \
            (f"{cls.name}.{node.name}" if cls else node.name)
        fn = FunctionNode(qname=qname, module=self.mod, name=node.name,
                          cls=cls.name if cls else None,
                          lineno=node.lineno, node=node)
        fn.is_callback = node.name in MID_TRANSITION_CALLBACKS
        a = node.args
        fn.params = tuple(p.arg for p in
                          list(a.posonlyargs) + list(a.args)
                          + list(a.kwonlyargs))
        for dec in node.decorator_list:
            target = dec.func if isinstance(dec, ast.Call) else dec
            d = _dotted(target) or ""
            tail = d.rsplit(".", 1)[-1]
            if tail == "run" and ("elastic" in d or d == "run"):
                fn.uses_elastic_state = True
        if cls is not None:
            cls.methods[node.name] = fn
        elif len(self._fn_stack) == 1:      # genuine top-level def
            self.mod.functions[node.name] = fn
        else:                                # nested def: best-effort by name
            self.mod.functions.setdefault(node.name, fn)
        self.mod.all_functions.append(fn)

        facts = _FunctionFacts()
        facts.visit(node)
        self._fn_stack.append(fn)
        self._taint_stack.append(facts.tainted)
        self._early_exit.append(None)
        saved_guards = self._guard_stack
        self._guard_stack = []      # a def body does not run at the def site
        self.generic_visit(node)
        self._guard_stack = saved_guards
        self._early_exit.pop()
        self._taint_stack.pop()
        self._fn_stack.pop()

    visit_FunctionDef = _visit_function
    visit_AsyncFunctionDef = _visit_function

    def visit_ClassDef(self, node: ast.ClassDef):
        info = ClassInfo(
            name=node.name, qname=f"{self.mod.modname}:{node.name}",
            module=self.mod,
            bases=[b for b in (_dotted(x) for x in node.bases) if b])
        self.mod.classes[node.name] = info
        self._class_stack.append(info)
        self.generic_visit(node)
        self._class_stack.pop()

    # ----------------------------------------------------------- imports
    def visit_Import(self, node: ast.Import):
        # Imports anywhere in the file bind module-wide (best effort).
        for alias in node.names:
            name = alias.asname or alias.name.split(".")[0]
            target = alias.name if alias.asname else alias.name.split(".")[0]
            self.mod.imports[name] = target
        self.generic_visit(node)

    def visit_ImportFrom(self, node: ast.ImportFrom):
        base = node.module or ""
        if node.level:
            base = _resolve_relative(self.mod.package or self.mod.modname,
                                     node.level, node.module)
        for alias in node.names:
            if alias.name == "*":
                continue
            name = alias.asname or alias.name
            self.mod.imports[name] = f"{base}.{alias.name}" if base \
                else alias.name
        self.generic_visit(node)

    # --------------------------------------------------- rank-guard flow
    def _branch_has_exit(self, body: Sequence[ast.stmt]) -> bool:
        for stmt in body:
            for sub in ast.walk(stmt):
                if isinstance(sub, (ast.Return, ast.Raise, ast.Continue,
                                    ast.Break)):
                    return True
                if isinstance(sub, ast.Call) and _call_name(sub) in (
                        "exit", "_exit", "abort"):
                    return True
        return False

    def _visit_divergent(self, node, bodies=()):
        divergent = _mentions_rank(node.test, self._taint_stack[-1])
        if divergent:
            self._guard_stack.append(Guard(line=node.lineno, kind="branch"))
        self.generic_visit(node)
        if divergent:
            self._guard_stack.pop()
            if isinstance(node, ast.If) and self._early_exit[-1] is None \
                    and (self._branch_has_exit(node.body)
                         or (node.orelse
                             and self._branch_has_exit(node.orelse))):
                self._early_exit[-1] = Guard(
                    line=node.end_lineno or node.lineno, kind="early-exit")

    visit_If = _visit_divergent
    visit_While = _visit_divergent
    visit_IfExp = _visit_divergent

    # ------------------------------------------------- process-set values
    def _ps_scopes(self) -> List["FunctionNode"]:
        scopes = [self._cur()]
        if self.mod.toplevel is not None \
                and self._cur() is not self.mod.toplevel:
            scopes.append(self.mod.toplevel)
        return scopes

    def _resolve_ps(self, expr: ast.AST) -> ProcessSetValue:
        """Abstract-evaluate a ``process_set=`` argument expression."""
        if isinstance(expr, ast.Constant) and expr.value is None:
            return WORLD
        if isinstance(expr, ast.Call):
            cname = _call_name(expr)
            if cname in ("add_process_set", "ProcessSet"):
                return ProcessSetValue("named", "<anonymous>",
                                       _literal_ranks(expr))
            return ProcessSetValue("unknown", cname or "<call>")
        if isinstance(expr, ast.Name):
            for scope in self._ps_scopes():
                if expr.id in scope.ps_bindings:
                    return scope.ps_bindings[expr.id]
            if expr.id in self._cur().params:
                return ProcessSetValue("param", expr.id)
            return ProcessSetValue("unknown", expr.id)
        d = _dotted(expr)
        return ProcessSetValue("unknown", d or "<expr>")

    def _lookup_axis(self, axis: str) -> Optional[ProcessSetValue]:
        for scope in self._ps_scopes():
            if axis in scope.axis_bindings:
                return scope.axis_bindings[axis]
        return None

    # --------------------------------------------------------- bindings
    @staticmethod
    def _is_sharded_opt_call(val: ast.Call) -> Any:
        """The sharding mode a binding value yields, or False: the zero
        wrappers themselves (sharded_optimizer → True,
        full_sharded_optimizer → "full"), or DistributedOptimizer with a
        constant sharded= whose value is the mode (non-constant sharded=
        is HVD110's territory)."""
        name = _call_name(val)
        if name == "sharded_optimizer":
            return True
        if name == "full_sharded_optimizer":
            return "full"
        if name == "DistributedOptimizer":
            for kw in val.keywords:
                if kw.arg == "sharded" and isinstance(kw.value,
                                                      ast.Constant):
                    v = kw.value.value
                    if v == "full":
                        return "full"
                    return bool(v)
        return False

    def visit_Assign(self, node: ast.Assign):
        if len(node.targets) == 1 and isinstance(node.targets[0], ast.Name):
            tgt = node.targets[0].id
            val = node.value
            # ANY rebind clears a sharded-optimizer marking first (a
            # Name/None/attribute reassignment must not leave a stale
            # entry registering phantom sharded_update sites).  Same for
            # stale process-set / partial-pin entries.
            self._cur().sharded_opt_vars.pop(tgt, None)
            self._cur().ps_bindings.pop(tgt, None)
            self._cur().partial_ps.pop(tgt, None)
            if isinstance(val, ast.Call):
                cname = _call_name(val)
                if cname in ("add_process_set", "ProcessSet"):
                    self._cur().ps_bindings[tgt] = ProcessSetValue(
                        "named", tgt, _literal_ranks(val))
                elif cname == "partial":
                    for kw in val.keywords:
                        if kw.arg == "process_set":
                            self._cur().partial_ps[tgt] = \
                                self._resolve_ps(kw.value)
                mode = self._is_sharded_opt_call(val)
                if mode:
                    self._cur().sharded_opt_vars[tgt] = mode
                wrapped = unwrap_wrapped_callable(val)
                if wrapped is not None:
                    self._cur().bindings[tgt] = ("alias", wrapped)
                else:
                    d = _dotted(val.func)
                    if d:
                        self._cur().bindings[tgt] = ("instance", d)
            elif isinstance(val, ast.Name):
                self._cur().bindings[tgt] = ("alias", val.id)
                for scope in self._ps_scopes():
                    if val.id in scope.ps_bindings:
                        self._cur().ps_bindings[tgt] = \
                            scope.ps_bindings[val.id]
                        break
            elif isinstance(val, ast.Attribute):
                d = _dotted(val)
                if d:
                    self._cur().bindings[tgt] = ("alias", d)
                # ``axis = ps.axis_name``: the axis VARIABLE now carries
                # the set — in-graph collectives submitting over it are
                # set-scoped, not bare world (the jax/optimizer.py
                # pattern).  Keyed by variable name in the same table as
                # constant axis strings; _lookup_axis serves both.
                if val.attr == "axis_name" \
                        and isinstance(val.value, ast.Name):
                    base = self._resolve_ps(val.value)
                    if base.kind in ("named", "param"):
                        self._cur().axis_bindings[tgt] = base
        # self.attr = C(...) inside a method: class attribute type.
        if self._class_stack and len(node.targets) == 1 \
                and isinstance(node.targets[0], ast.Attribute) \
                and isinstance(node.targets[0].value, ast.Name) \
                and node.targets[0].value.id == "self" \
                and isinstance(node.value, ast.Call):
            d = _dotted(node.value.func)
            if d:
                self._class_stack[-1].attr_types[node.targets[0].attr] = d
        self.generic_visit(node)

    # ------------------------------------------------------------- calls
    def visit_Call(self, node: ast.Call):
        fn = self._cur()
        name = _call_name(node)
        if name:
            fn.called_names.add(name)
            if name == "init" and not self.mod.init_line:
                self.mod.init_line = node.lineno
            if name in _TRAINING_WRAPPERS and not self.mod.first_training_line:
                self.mod.first_training_line = node.lineno
            if name in ("JaxState", "TorchState", "TensorFlowKerasState"):
                fn.uses_elastic_state = True
        if name == "process_set_mesh":
            # ``m = process_set_mesh(evens, axis_name="dp")`` binds the
            # mesh axis "dp" to the set's value: in-graph collectives over
            # that axis_name submit on the set's lane.
            ps_arg: Optional[ast.AST] = node.args[0] if node.args else None
            axis: Optional[str] = None
            for kw in node.keywords:
                if kw.arg == "process_set":
                    ps_arg = kw.value
                elif kw.arg == "axis_name" \
                        and isinstance(kw.value, ast.Constant) \
                        and isinstance(kw.value.value, str):
                    axis = kw.value.value
            if axis is not None:
                fn.axis_bindings[axis] = (
                    self._resolve_ps(ps_arg) if ps_arg is not None
                    else WORLD)
        if name in COLLECTIVE_NAMES:
            ps = WORLD
            has_ps = False
            for kw in node.keywords:
                if kw.arg == "process_set":
                    has_ps = True
                    ps = self._resolve_ps(kw.value)
            if not has_ps:
                # Positional forwarding: a registered-set name (or the
                # enclosing function's own process_set parameter) passed
                # positionally still scopes the site — the eager-op
                # wrappers thread process_set positionally, and treating
                # them as bare world sites would false-positive HVD113.
                for arg in node.args:
                    if not isinstance(arg, ast.Name):
                        continue
                    v: Optional[ProcessSetValue] = None
                    for scope in self._ps_scopes():
                        if arg.id in scope.ps_bindings:
                            v = scope.ps_bindings[arg.id]
                            break
                    if v is None and "process_set" in arg.id \
                            and arg.id in self._cur().params:
                        v = ProcessSetValue("param", arg.id)
                    if v is not None:
                        ps = v
                        break
            if not has_ps and ps is WORLD:
                # In-graph form: an axis_name bound by a process_set_mesh
                # in scope (constant) or carrying ``ps.axis_name`` (axis
                # variable) pins the site to that lane.
                for kw in node.keywords:
                    if kw.arg != "axis_name":
                        continue
                    key: Optional[str] = None
                    if isinstance(kw.value, ast.Constant) \
                            and isinstance(kw.value.value, str):
                        key = kw.value.value
                    elif isinstance(kw.value, ast.Name):
                        key = kw.value.id
                    if key is not None:
                        bound = self._lookup_axis(key)
                        if bound is not None:
                            ps = bound
            fn.collectives.append(CollectiveSite(
                name=name, line=node.lineno, col=node.col_offset + 1,
                guard=self._cur_guard(),
                has_process_set=has_ps,
                sharded=next(
                    (("full" if kw.value.value == "full"
                      else bool(kw.value.value))
                     for kw in node.keywords
                     if kw.arg == "sharded"
                     and isinstance(kw.value, ast.Constant)
                     and kw.value.value),
                    False),
                hierarchical=any(kw.arg == "hierarchical"
                                 and isinstance(kw.value, ast.Constant)
                                 and bool(kw.value.value)
                                 for kw in node.keywords),
                ps=ps))
        elif name in ("update", "apply_gradients"):
            # opt.update(...) on a name bound to DistributedOptimizer(
            # sharded=True) / sharded_optimizer: a synthetic sharded_update
            # site — the schedule pass expands it to the reduce-scatter +
            # allgather sequence the eager pipeline actually submits, so
            # HVD108/HVD109 model the sharded data plane, not an allreduce.
            d = _dotted(node.func)
            head = d.split(".")[0] if d else None
            scopes = [fn.sharded_opt_vars]
            if self.mod.toplevel is not None and fn is not self.mod.toplevel:
                scopes.append(self.mod.toplevel.sharded_opt_vars)
            mode = next((s[head] for s in scopes
                         if head is not None and head in s), False)
            if mode:
                fn.collectives.append(CollectiveSite(
                    name="sharded_update", line=node.lineno,
                    col=node.col_offset + 1, guard=self._cur_guard(),
                    has_process_set=False, sharded=mode))
        ps_kwarg: Optional[ProcessSetValue] = None
        for kw in node.keywords:
            if kw.arg == "process_set":
                ps_kwarg = self._resolve_ps(kw.value)
        callee_expr = _dotted(node.func)
        if ps_kwarg is None and callee_expr:
            head = callee_expr.split(".")[0]
            for scope in self._ps_scopes():
                if head in scope.partial_ps:
                    ps_kwarg = scope.partial_ps[head]
                    break
        fn.calls.append(CallSite(
            callee_expr=callee_expr, line=node.lineno,
            col=node.col_offset + 1, guard=self._cur_guard(),
            ps_kwarg=ps_kwarg))
        # Functions handed to TRANSITION registrars become transition
        # callbacks themselves.  register_reset_callbacks is deliberately
        # not here: reset callbacks run post-re-rendezvous (same reasoning
        # as excluding ``on_reset`` from MID_TRANSITION_CALLBACKS).
        if name in ("register_transition_callbacks", "register_leave_hooks",
                    "register_preempt_hooks", "on_generation_change"):
            for arg in node.args:
                elts = arg.elts if isinstance(arg, (ast.List, ast.Tuple)) \
                    else [arg]
                for e in elts:
                    d = _dotted(e)
                    if d:
                        fn.bindings.setdefault(
                            f"<cb:{d}>", ("callback", d))
        self.generic_visit(node)


# ---------------------------------------------------------------------------
# Build + link
# ---------------------------------------------------------------------------

def build_package(paths: Sequence[str]) -> Package:
    """Parse every ``.py`` under ``paths`` and link the call graph."""
    modules: Dict[str, ModuleInfo] = {}
    all_modules: List[ModuleInfo] = []
    for f in iter_python_files(paths):
        try:
            with open(f, "r", encoding="utf-8") as fh:
                source = fh.read()
            tree = ast.parse(source, filename=f)
        except (OSError, SyntaxError):
            continue                 # per-module lint reports HVD100
        modname = module_name_for(f)
        # An __init__.py IS its package (relative imports resolve against
        # the full dotted name); any other module's package is its parent.
        if os.path.basename(f) == "__init__.py":
            package = modname
        elif "." in modname:
            package = modname.rsplit(".", 1)[0]
        else:
            package = ""
        mod = ModuleInfo(path=os.path.abspath(f), modname=modname,
                         package=package, source=source)
        mod.suppressed = _suppressed_lines(source)
        modules.setdefault(modname, mod)     # resolution map: first wins
        all_modules.append(mod)              # analysis set: every file
        _Collector(mod, tree).visit(tree)

    pkg = Package(modules=modules, functions={}, classes={},
                  all_modules=all_modules)
    for mod in all_modules:
        for fn in mod.all_functions:
            pkg.functions.setdefault(fn.qname, fn)
        for cls in mod.classes.values():
            pkg.classes.setdefault(cls.qname, cls)
    _link(pkg)
    return pkg


def _split_module_prefix(pkg: Package, dotted: str
                         ) -> Tuple[Optional[ModuleInfo], List[str]]:
    """Longest analyzed-module prefix of a dotted path + leftover parts."""
    parts = dotted.split(".")
    for i in range(len(parts), 0, -1):
        mod = pkg.modules.get(".".join(parts[:i]))
        if mod is not None:
            return mod, parts[i:]
    return None, parts


def _resolve_in_module(pkg: Package, mod: ModuleInfo, parts: List[str],
                       depth: int = 0):
    """Resolve a symbol path inside a module: function, class, alias or
    re-exported import — chased across modules with a depth bound."""
    if depth > 10 or not parts:
        return None
    head, rest = parts[0], parts[1:]
    if not rest:
        if head in mod.functions:
            return mod.functions[head]
        if head in mod.classes:
            return mod.classes[head]
    else:
        cls = mod.classes.get(head)
        if cls is not None:
            return _method_lookup(pkg, cls, rest[0]) if len(rest) == 1 \
                else None
    binding = (mod.toplevel.bindings.get(head)
               if mod.toplevel is not None else None)
    if binding is not None and binding[0] == "alias":
        return _resolve_dotted(pkg, mod, binding[1].split(".") + rest,
                               depth + 1)
    if head in mod.imports:
        target = mod.imports[head].split(".") + rest
        tmod, leftover = _split_module_prefix(pkg, ".".join(target))
        if tmod is not None:
            if not leftover:
                return tmod
            return _resolve_in_module(pkg, tmod, leftover, depth + 1)
    return None


def _resolve_dotted(pkg: Package, mod: ModuleInfo, parts: List[str],
                    depth: int = 0):
    if depth > 10:
        return None
    return _resolve_in_module(pkg, mod, parts, depth)


def _method_lookup(pkg: Package, cls: ClassInfo, method: str,
                   depth: int = 0) -> Optional[FunctionNode]:
    if depth > 5:
        return None
    if method in cls.methods:
        return cls.methods[method]
    for base in cls.bases:
        resolved = _resolve_dotted(pkg, cls.module, base.split("."))
        if isinstance(resolved, ClassInfo):
            found = _method_lookup(pkg, resolved, method, depth + 1)
            if found is not None:
                return found
    return None


def _resolve_call(pkg: Package, fn: FunctionNode, expr: str
                  ) -> Optional[FunctionNode]:
    mod = fn.module
    parts = expr.split(".")
    head = parts[0]

    # self.m(...) / self.attr.m(...)
    if head == "self" and fn.cls:
        cls = mod.classes.get(fn.cls)
        if cls is None:
            return None
        if len(parts) == 2:
            return _method_lookup(pkg, cls, parts[1])
        if len(parts) == 3 and parts[1] in cls.attr_types:
            target = _resolve_dotted(
                pkg, mod, cls.attr_types[parts[1]].split("."))
            if isinstance(target, ClassInfo):
                return _method_lookup(pkg, target, parts[2])
        return None

    # Local binding: alias chain or instance method.
    scopes = [fn.bindings]
    if mod.toplevel is not None and fn is not mod.toplevel:
        scopes.append(mod.toplevel.bindings)
    for bindings in scopes:
        b = bindings.get(head)
        if b is None:
            continue
        kind, target = b
        if kind == "alias":
            resolved = _resolve_dotted(pkg, mod, target.split(".") + parts[1:])
            if isinstance(resolved, FunctionNode):
                return resolved
            if isinstance(resolved, ClassInfo) and len(parts) == 1:
                return _method_lookup(pkg, resolved, "__init__")
        elif kind == "instance" and len(parts) == 2:
            resolved = _resolve_dotted(pkg, mod, target.split("."))
            if isinstance(resolved, ClassInfo):
                return _method_lookup(pkg, resolved, parts[1])
        break

    resolved = _resolve_dotted(pkg, mod, parts)
    if isinstance(resolved, FunctionNode):
        return resolved
    if isinstance(resolved, ClassInfo):
        return _method_lookup(pkg, resolved, "__init__")
    return None


def _link(pkg: Package) -> None:
    for fn in list(pkg.iter_functions()):
        for cs in fn.calls:
            if not cs.callee_expr:
                continue
            target = _resolve_call(pkg, fn, cs.callee_expr)
            if target is not None and target is not fn:
                cs.resolved = target
                target.in_edges += 1
        # Registered callbacks: mark the handed function.
        for key, (kind, target) in list(fn.bindings.items()):
            if kind == "callback":
                resolved = _resolve_call(pkg, fn, target)
                if isinstance(resolved, FunctionNode):
                    resolved.is_callback = True


def reachable(fn: FunctionNode, max_depth: int = 16
              ) -> Iterable[Tuple[FunctionNode, Tuple[CallSite, ...]]]:
    """All functions reachable from ``fn`` through resolved call edges,
    yielded with the (first-found, shortest) call-site chain leading there.
    Bounded BFS; ``fn`` itself is not yielded."""
    seen: Set[str] = {fn.qname}
    frontier: List[Tuple[FunctionNode, Tuple[CallSite, ...]]] = [(fn, ())]
    depth = 0
    while frontier and depth < max_depth:
        nxt: List[Tuple[FunctionNode, Tuple[CallSite, ...]]] = []
        for cur, chain in frontier:
            for cs in cur.calls:
                t = cs.resolved
                if t is None or t.qname in seen:
                    continue
                seen.add(t.qname)
                yield t, chain + (cs,)
                nxt.append((t, chain + (cs,)))
        frontier = nxt
        depth += 1


def _uniform_expr(node: ast.AST, uniform_names: Set[str]) -> bool:
    if isinstance(node, ast.Constant):
        return True
    if isinstance(node, ast.Name):
        return node.id == "__name__" or node.id in uniform_names
    if isinstance(node, ast.Call):
        return _call_name(node) in _UNIFORM_CALLS and \
            all(_uniform_expr(a, uniform_names) for a in node.args) and \
            not node.keywords
    if isinstance(node, ast.Compare):
        return _uniform_expr(node.left, uniform_names) and \
            all(_uniform_expr(c, uniform_names) for c in node.comparators)
    if isinstance(node, ast.BoolOp):
        return all(_uniform_expr(v, uniform_names) for v in node.values)
    if isinstance(node, ast.UnaryOp):
        return _uniform_expr(node.operand, uniform_names)
    if isinstance(node, ast.BinOp):
        return _uniform_expr(node.left, uniform_names) and \
            _uniform_expr(node.right, uniform_names)
    return False


def is_uniform_test(test: ast.AST, tainted: Set[str],
                    uniform_names: Optional[Set[str]] = None) -> bool:
    """True when a branch condition is provably identical on every rank
    (HVD108 exemption): built only from constants, ``__name__`` checks,
    world-size-style accessors and names assigned from them
    (``size = hvd.size(); if size >= 2:``).  Rank-divergent tests are
    HVD101's domain and also return True here (already reported there)."""
    if _mentions_rank(test, tainted):
        return True
    return _uniform_expr(test, uniform_names or set())
