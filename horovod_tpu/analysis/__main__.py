"""CLI: ``python -m horovod_tpu.analysis [paths...]``.

Lints the given files/directories for deadlock-prone collective patterns
and prints findings with severity and fix hints.  Exit status: 0 clean (or
warnings only, unless ``--strict``), 1 on error-severity findings, 2 on
usage errors.

The lint layer is pure AST analysis: nothing is executed, no runtime is
initialized and no device is touched — safe to run in CI.
"""

from __future__ import annotations

import argparse
import json
import sys

from .collective_lint import lint_paths
from .findings import RULES, summarize


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="python -m horovod_tpu.analysis",
        description="Static collective-correctness linter for horovod_tpu "
                    "training scripts.")
    ap.add_argument("paths", nargs="*",
                    help="Python files or directories to lint")
    ap.add_argument("--json", action="store_true",
                    help="machine-readable output")
    ap.add_argument("--no-fix-hints", action="store_true",
                    help="omit fix guidance lines")
    ap.add_argument("--disable", default="",
                    help="comma-separated rule IDs to ignore (e.g. "
                         "HVD105,HVD103)")
    ap.add_argument("--strict", action="store_true",
                    help="exit non-zero on warnings too")
    ap.add_argument("--list-rules", action="store_true",
                    help="print the rule catalog and exit")
    args = ap.parse_args(argv)

    if args.list_rules:
        for rule in RULES.values():
            print(f"{rule.id} [{rule.severity.value}] {rule.title}")
            print(f"    {rule.rationale}")
            print(f"    fix: {rule.fix_hint}")
        return 0

    if not args.paths:
        ap.print_usage()
        return 2

    disabled = {s.strip().upper() for s in args.disable.split(",") if s.strip()}
    try:
        findings = [f for f in lint_paths(args.paths) if f.rule not in disabled]
    except OSError as e:
        print(f"error: {e}", file=sys.stderr)
        return 2

    if args.json:
        print(json.dumps([{
            "rule": f.rule, "severity": f.severity.value, "path": f.path,
            "line": f.line, "col": f.col, "message": f.message,
            "fix_hint": f.fix_hint,
        } for f in findings], indent=2))
    else:
        for f in findings:
            print(f.render(show_fix=not args.no_fix_hints))
        print(summarize(findings))

    if any(f.is_error for f in findings):
        return 1
    if args.strict and findings:
        return 1
    return 0


if __name__ == "__main__":
    sys.exit(main())
