"""CLI: ``python -m horovod_tpu.analysis [paths...]``.

Lints the given files/directories for deadlock-prone collective patterns
and prints findings with severity and fix hints.  ``--whole-package``
additionally runs the two-pass interprocedural analysis (call-graph
rank-guard propagation, cross-module HVD102/HVD103 facts, HVD108/HVD109
schedule checks) over the whole file set.

Exit status (CI contract):
  0  clean (or warnings only, unless ``--strict``)
  1  error-severity findings (with ``--baseline``: NEW findings of any
     severity)
  2  usage errors (bad paths, bad flags)
  3  the analyzer itself crashed — distinct from lint failures so CI
     consumers can page the analyzer's owners instead of the author of
     the change under test

The lint layer is pure AST analysis: nothing is executed, no runtime is
initialized and no device is touched — safe to run in CI.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import traceback

from .findings import RULES, summarize

EXIT_CLEAN, EXIT_FINDINGS, EXIT_USAGE, EXIT_INTERNAL = 0, 1, 2, 3


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="python -m horovod_tpu.analysis",
        description="Static collective-correctness linter for horovod_tpu "
                    "training scripts.")
    ap.add_argument("paths", nargs="*",
                    help="Python files or directories to lint")
    ap.add_argument("--whole-package", action="store_true",
                    help="two-pass interprocedural mode: call-graph "
                         "rank-guard propagation, cross-module facts, "
                         "HVD108/HVD109 schedule checks")
    ap.add_argument("--json", action="store_true",
                    help="machine-readable output")
    ap.add_argument("--sarif", metavar="FILE",
                    help="write findings as SARIF 2.1.0 to FILE (for CI "
                         "annotation); with --baseline, only NEW findings")
    ap.add_argument("--baseline", metavar="FILE",
                    help="subtract the baseline file: only findings not "
                         "listed there count (and fail the exit status)")
    ap.add_argument("--write-baseline", metavar="FILE",
                    help="write the current findings to FILE as a baseline "
                         "and exit 0")
    ap.add_argument("--emit-static-index", metavar="FILE",
                    help="(whole-package) write the call-site -> static "
                         "call-graph node map consumed by "
                         "HVD_TPU_SANITIZER_STATIC_INDEX")
    ap.add_argument("--root", default=None,
                    help="repo root for relative paths in SARIF/baseline "
                         "output (default: common prefix of the inputs)")
    ap.add_argument("--no-fix-hints", action="store_true",
                    help="omit fix guidance lines")
    ap.add_argument("--disable", default="",
                    help="comma-separated rule IDs to ignore (e.g. "
                         "HVD105,HVD103)")
    ap.add_argument("--strict", action="store_true",
                    help="exit non-zero on warnings too")
    ap.add_argument("--list-rules", action="store_true",
                    help="print the rule catalog and exit")
    args = ap.parse_args(argv)

    if args.list_rules:
        for rule in RULES.values():
            print(f"{rule.id} [{rule.severity.value}] {rule.title}")
            print(f"    {rule.rationale}")
            print(f"    fix: {rule.fix_hint}")
        return EXIT_CLEAN

    if not args.paths:
        ap.print_usage()
        return EXIT_USAGE

    disabled = {s.strip().upper() for s in args.disable.split(",")
                if s.strip()}
    if args.root is None and (args.baseline or args.write_baseline
                              or args.sarif):
        # The documented default: baselines/SARIF must be portable across
        # checkouts, so relativize against the inputs' common prefix.
        common = os.path.commonpath([os.path.abspath(p)
                                     for p in args.paths])
        args.root = common if os.path.isdir(common) \
            else os.path.dirname(common)
    try:
        if args.whole_package:
            from .whole_package import analyze_package, build_package, \
                build_static_index
            pkg = build_package(args.paths)
            findings = analyze_package(args.paths, package=pkg)
            if args.emit_static_index:
                index = build_static_index(args.paths, package=pkg,
                                           findings=findings)
                with open(args.emit_static_index, "w",
                          encoding="utf-8") as fh:
                    json.dump(index, fh, indent=2, sort_keys=True)
        else:
            from .collective_lint import lint_paths
            findings = lint_paths(args.paths)
            if args.emit_static_index:
                print("error: --emit-static-index requires --whole-package",
                      file=sys.stderr)
                return EXIT_USAGE
        findings = [f for f in findings if f.rule not in disabled]
    except OSError as e:
        print(f"error: {e}", file=sys.stderr)
        return EXIT_USAGE
    except Exception:  # noqa: BLE001 - CI contract: crashes are NOT findings
        print("internal error: the analyzer crashed (exit 3); this is an "
              "analyzer bug, not a finding in the code under test",
              file=sys.stderr)
        traceback.print_exc()
        return EXIT_INTERNAL

    try:
        if args.write_baseline:
            from .baseline import write_baseline
            write_baseline(findings, args.write_baseline, root=args.root)
            print(f"wrote baseline with {len(findings)} finding(s) to "
                  f"{args.write_baseline}")
            return EXIT_CLEAN

        baselined = 0
        stale = []
        if args.baseline:
            from .baseline import diff_baseline, load_baseline
            diff = diff_baseline(findings, load_baseline(args.baseline),
                                 root=args.root)
            baselined, stale = len(diff.matched), diff.stale
            findings = diff.new

        if args.sarif:
            from .sarif import write_sarif
            write_sarif(findings, args.sarif, root=args.root)
    except Exception:  # noqa: BLE001
        print("internal error: the analyzer crashed (exit 3)",
              file=sys.stderr)
        traceback.print_exc()
        return EXIT_INTERNAL

    if args.json:
        print(json.dumps([{
            "rule": f.rule, "severity": f.severity.value, "path": f.path,
            "line": f.line, "col": f.col, "message": f.message,
            "fix_hint": f.fix_hint,
        } for f in findings], indent=2))
    else:
        for f in findings:
            print(f.render(show_fix=not args.no_fix_hints))
        tail = summarize(findings)
        if args.baseline:
            tail += f" (+{baselined} baselined)"
            if stale:
                tail += f"; {len(stale)} stale baseline entr" + \
                    ("y" if len(stale) == 1 else "ies") + \
                    " no longer fire(s): " + \
                    ", ".join(f"{r}@{p}:{ln}" for r, p, ln in stale[:5])
        print(tail)

    if args.baseline:
        return EXIT_FINDINGS if findings else EXIT_CLEAN
    if any(f.is_error for f in findings):
        return EXIT_FINDINGS
    if args.strict and findings:
        return EXIT_FINDINGS
    return EXIT_CLEAN


if __name__ == "__main__":
    sys.exit(main())
