"""Pass 2 of the whole-package analyzer: interprocedural fact propagation.

Runs on the symbol table + call graph from :mod:`.callgraph` and closes the
gaps per-module lint cannot see:

- **HVD101, interprocedural** — a collective inside a helper that is only
  *called* from a rank-guarded branch (possibly across modules, through
  aliases/partials/methods) is flagged at the collective site with the
  guarded call chain spelled out.  Context-bounded: a helper called from
  both guarded and unguarded sites reports only the guarded path — the
  guard context travels along each chain instead of being merged into the
  callee.
- **HVD102/HVD103, cross-module** — process-set registration and
  initial-broadcast facts are unioned over each entry point's call-graph
  closure.  A training script whose ``broadcast_parameters`` lives in a
  helper module stops false-positiving; one whose ``init()`` and
  ``DistributedOptimizer`` are split across modules starts firing.
- **HVD108** — per entry point, a *collective schedule* (the sequence of
  collectives reachable along each branch) is computed; two paths through
  one function that emit different sequences are flagged unless the branch
  condition is provably rank-invariant.
- **HVD109** — collectives reachable from elastic/churn transition
  callbacks (``on_leave``/``new_generation``/... or functions handed to
  ``register_reset_callbacks``), where the rank set is mid-transition.

``build_static_index`` exports a call-site → static-node map that the
runtime sanitizer (``HVD_TPU_SANITIZER_STATIC_INDEX``) folds into its
ledger reports, so a runtime divergence names the static finding that
would have caught it.
"""

from __future__ import annotations

import os
from typing import Dict, List, Optional, Sequence, Set, Tuple

from .callgraph import (
    CallSite, CollectiveSite, FunctionNode, ModuleInfo, Package,
    build_package, is_uniform_test, reachable,
)
from .collective_lint import (
    _FunctionFacts, _SYNC_CALLS, _TRAINING_WRAPPERS, lint_file,
)
from .findings import Finding

_MAX_CHAIN = 16          # call-graph propagation depth bound
_MAX_SCHEDULE_DEPTH = 10  # schedule splice depth bound


def _site_events(col: CollectiveSite) -> List:
    """Schedule events a collective site actually submits (ISSUE 15).

    A ``sharded_update`` site (``opt.update(...)`` on a
    ``DistributedOptimizer(sharded=True)`` / ``sharded_optimizer``
    binding) schedules the ZeRO pipeline — reduce-scatter then allgather,
    never an allreduce.  Sharded collectives carry the ``[sharded]``
    dimension their fusion key / negotiation digest carries: a sharded
    reduce-scatter and an unsharded one of the same shapes are DIFFERENT
    programs, so schedules comparing them must diverge."""
    if col.name == "sharded_update":
        return [("op", "reducescatter[sharded]"),
                ("op", "allgather[sharded]")]
    if col.sharded:
        return [("op", f"{col.name}[sharded]")]
    return [("op", col.name)]


def _suppressed(mod: ModuleInfo, line: int, rule: str) -> bool:
    ids = mod.suppressed.get(line, set())
    return "ALL" in ids or rule in ids


def _chain_str(entry: FunctionNode, chain: Sequence[CallSite],
               target: FunctionNode) -> str:
    hops = [f"{entry.module.base}:{chain[0].line}" if chain else
            entry.module.base]
    for cs in chain[1:]:
        hops.append(f"{cs.callee_expr or '?'}()")
    hops.append(f"{target.name}() [{target.module.base}:{target.lineno}]")
    return " -> ".join(hops)


# ---------------------------------------------------------------------------
# HVD101: rank-guard propagation along the call graph
# ---------------------------------------------------------------------------

def _interprocedural_hvd101(pkg: Package) -> List[Finding]:
    findings: List[Finding] = []
    best: Dict[Tuple[str, int], Tuple[int, Finding]] = {}
    for fn in pkg.iter_functions():
        for cs in fn.calls:
            if cs.guard is None or cs.resolved is None:
                continue
            # BFS from the guarded callee; the guard context belongs to
            # THIS chain only (bounded context-sensitivity): other call
            # sites of the same helper stay unguarded.
            targets = [(cs.resolved, (cs,))]
            targets += [(t, (cs,) + chain)
                        for t, chain in reachable(cs.resolved,
                                                  max_depth=_MAX_CHAIN)]
            for target, chain in targets:
                for col in target.collectives:
                    if col.guard is not None:
                        continue        # already flagged intra-procedurally
                    if _suppressed(target.module, col.line, "HVD101") or \
                            _suppressed(fn.module, cs.line, "HVD101"):
                        continue
                    key = (target.module.path, col.line)
                    f = Finding(
                        rule="HVD101", path=target.module.path,
                        line=col.line, col=col.col,
                        message=(
                            f"collective {col.name!r} is only reached "
                            f"through a rank-guarded call chain "
                            f"({cs.guard.describe(fn.module.base)}): "
                            f"{_chain_str(fn, chain, target)} — only a "
                            f"subset of ranks submits it, the rest of the "
                            f"world blocks in negotiation"))
                    prev = best.get(key)
                    if prev is None or len(chain) < prev[0]:
                        best[key] = (len(chain), f)
    findings.extend(f for _, f in best.values())
    return findings


# ---------------------------------------------------------------------------
# HVD102/HVD103: entry-closure fact flow
# ---------------------------------------------------------------------------

def _entry_roots(mod: ModuleInfo) -> List[FunctionNode]:
    """Closure roots of a module: its top level plus every function defined
    in it that no analyzed code calls (externally invokable — ``main()``
    behind an ``if __name__`` block, CLI handlers, callbacks)."""
    roots = [mod.toplevel] if mod.toplevel is not None else []
    roots += [fn for fn in mod.all_functions
              if fn is not mod.toplevel and fn.in_edges == 0]
    return roots


def _closure(mod: ModuleInfo) -> List[FunctionNode]:
    out: List[FunctionNode] = []
    seen: Set[str] = set()
    for root in _entry_roots(mod):
        if root.qname not in seen:
            seen.add(root.qname)
            out.append(root)
        for t, _chain in reachable(root, max_depth=_MAX_CHAIN):
            if t.qname not in seen:
                seen.add(t.qname)
                out.append(t)
    return out


def _closure_facts_hvd102_103(pkg: Package) -> List[Finding]:
    findings: List[Finding] = []
    for mod in pkg.all_modules:
        closure = _closure(mod)
        names: Set[str] = set()
        elastic = False
        for fn in closure:
            names |= fn.called_names
            elastic = elastic or fn.uses_elastic_state

        # HVD103 over the closure: init + gradient reduction anywhere in
        # reach, no state sync anywhere in reach.
        if "init" in names and (names & _TRAINING_WRAPPERS) \
                and not (names & _SYNC_CALLS) and not elastic:
            line = mod.first_training_line or mod.init_line or 1
            if not _suppressed(mod, line, "HVD103"):
                findings.append(Finding(
                    rule="HVD103", path=mod.path, line=line, col=1,
                    message=(
                        "entry point calls init() and reduces gradients "
                        "(directly or through its call-graph closure) but "
                        "never broadcasts initial state from rank 0; ranks "
                        "train divergent models")))

        # HVD102 cross-module: the closure registers subgroup process sets
        # somewhere, and THIS module's own code submits bare collectives.
        # (Same-module registration is per-module lint's job — skip it to
        # avoid duplicate findings.)
        own_names: Set[str] = set()
        for fn in mod.all_functions:
            own_names |= fn.called_names
        if "add_process_set" in names and "add_process_set" not in own_names:
            for fn in mod.all_functions:
                for col in fn.collectives:
                    if col.has_process_set or \
                            _suppressed(mod, col.line, "HVD102"):
                        continue
                    findings.append(Finding(
                        rule="HVD102", path=mod.path, line=col.line,
                        col=col.col,
                        message=(
                            f"collective {col.name!r} omits process_set= "
                            f"while this entry point's call-graph closure "
                            f"registers subgroup process sets (in another "
                            f"module); it targets the GLOBAL set — a "
                            f"deadlock if only subgroup members reach "
                            f"this call")))
    return findings


# ---------------------------------------------------------------------------
# HVD108: collective schedules per branch
# ---------------------------------------------------------------------------

def _terminates(stmts) -> bool:
    """A statement list that definitely leaves the enclosing suite."""
    import ast
    return bool(stmts) and isinstance(
        stmts[-1], (ast.Return, ast.Raise, ast.Continue, ast.Break))


def _schedule_stmts(stmts, fn: FunctionNode, pkg: Package, memo, stack,
                    divergences, depth: int, collect: bool):
    """Schedule of a statement list.  ``collect`` gates divergence
    recording so each function's If nodes are reported once (when the
    function itself is analyzed), not re-reported at every splice site."""
    import ast
    seq: List = []
    calls_by_line: Dict[Tuple[int, int], FunctionNode] = {}
    for cs in fn.calls:
        if cs.resolved is not None:
            calls_by_line[(cs.line, cs.col)] = cs.resolved
    cols_by_line: Dict[Tuple[int, int], CollectiveSite] = {
        (c.line, c.col): c for c in fn.collectives}

    def expr_events(node) -> List:
        # Post-order: a call's arguments are evaluated (and their
        # collectives submitted) BEFORE the call itself completes, so
        # hvd.allgather(helper(x)) must record helper's ops first.
        ev: List = []

        def rec(n):
            for child in ast.iter_child_nodes(n):
                rec(child)
            if not isinstance(n, ast.Call):
                return
            key = (n.lineno, n.col_offset + 1)
            col = cols_by_line.get(key)
            if col is not None:
                ev.extend(_site_events(col))
                return
            target = calls_by_line.get(key)
            if target is not None:
                spliced = _schedule_of(target, pkg, memo, stack, depth + 1)
                if spliced is not None:
                    ev.append(spliced)

        rec(node)
        return [e for e in ev if e not in (("seq",), None)]

    def sub_sched(sub_stmts):
        return _schedule_stmts(sub_stmts, fn, pkg, memo, stack,
                               divergences, depth, collect)

    tainted = _fn_tainted(fn)
    i, n = 0, len(stmts)
    while i < n:
        stmt = stmts[i]
        i += 1
        if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.ClassDef)):
            continue                       # defs don't run at the def site
        if isinstance(stmt, ast.If):
            seq.extend(expr_events(stmt.test))
            body_t, or_t = _terminates(stmt.body), _terminates(stmt.orelse)
            if (body_t or or_t) and i < n:
                # Guard-clause folding: a terminating arm's real
                # alternative is the FALL-THROUGH code, not the lexical
                # orelse — `if fast: return coll(x)` vs the rest of the
                # function compare as two complete paths.
                rest = stmts[i:]
                a = _prune(sub_sched(
                    list(stmt.body) + ([] if body_t else rest)))
                b = _prune(sub_sched(
                    list(stmt.orelse) + ([] if or_t else rest)))
                i = n                      # rest is folded into the arms
            else:
                a = _prune(sub_sched(stmt.body))
                b = _prune(sub_sched(stmt.orelse))
            if a == b:
                if a is not None:
                    seq.append(a)
            else:
                if collect and not is_uniform_test(stmt.test, tainted,
                                                   _fn_uniform_names(fn)):
                    divergences.append((fn, stmt.lineno, a, b))
                seq.append(("branch", a or ("seq",), b or ("seq",)))
        elif isinstance(stmt, (ast.For, ast.AsyncFor, ast.While)):
            if isinstance(stmt, ast.While):
                seq.extend(expr_events(stmt.test))
            else:
                seq.extend(expr_events(stmt.iter))
            body = _schedule_stmts(stmt.body, fn, pkg, memo, stack,
                                   divergences, depth, collect)
            if len(body) > 1:
                seq.append(("loop", body))
            seq.extend(_schedule_stmts(stmt.orelse, fn, pkg, memo, stack,
                                       divergences, depth, collect)[1:])
        elif isinstance(stmt, ast.Try):
            seq.extend(_schedule_stmts(stmt.body, fn, pkg, memo, stack,
                                       divergences, depth, collect)[1:])
            # handlers model exceptional divergence — deliberately ignored
            seq.extend(_schedule_stmts(stmt.finalbody, fn, pkg, memo, stack,
                                       divergences, depth, collect)[1:])
        elif isinstance(stmt, (ast.With, ast.AsyncWith)):
            for item in stmt.items:
                seq.extend(expr_events(item.context_expr))
            seq.extend(_schedule_stmts(stmt.body, fn, pkg, memo, stack,
                                       divergences, depth, collect)[1:])
        else:
            seq.extend(expr_events(stmt))
    return tuple(["seq"] + seq)


def _prune(sched):
    """Normalize a schedule: drop every structural node (seq/branch/loop)
    that contains no collective op anywhere beneath it, and flatten nested
    sequences — so two branches that differ only in collective-free
    structure compare EQUAL (both prune to None).  Cycle markers prune
    away too: an unexpanded recursive call contributes no known ops."""
    if not isinstance(sched, tuple) or not sched:
        return None
    tag = sched[0]
    if tag == "op":
        return sched
    if tag == "seq":
        flat: List = []
        for item in sched[1:]:
            p = _prune(item)
            if p is None:
                continue
            if isinstance(p, tuple) and p and p[0] == "seq":
                flat.extend(p[1:])
            else:
                flat.append(p)
        return tuple(["seq"] + flat) if flat else None
    if tag == "branch":
        a, b = _prune(sched[1]), _prune(sched[2])
        if a is None and b is None:
            return None
        if a == b:
            return a
        return ("branch", a or ("seq",), b or ("seq",))
    if tag == "loop":
        body = _prune(sched[1])
        return None if body is None else ("loop", body)
    return None        # "cycle" and anything unknown


def _fn_tainted(fn: FunctionNode) -> Set[str]:
    cached = getattr(fn, "_tainted", None)
    if cached is None:
        facts = _FunctionFacts()
        if fn.node is not None:
            facts.visit(fn.node)
        cached = fn._tainted = facts.tainted
    return cached


def _fn_uniform_names(fn: FunctionNode) -> Set[str]:
    """Names assigned from world-size-style accessors — rank-invariant by
    construction, so branches on them don't diverge the schedule."""
    from .callgraph import _UNIFORM_CALLS
    cached = getattr(fn, "_uniform_names", None)
    if cached is None:
        facts = _FunctionFacts(source_calls=_UNIFORM_CALLS)
        if fn.node is not None:
            facts.visit(fn.node)
        cached = fn._uniform_names = facts.tainted
    return cached


# Reserved memo key counting cycle/depth truncations ("::" can't appear in
# a function qname, so it never collides with one).
_TRUNCATED = "::truncated::"


def _schedule_of(fn: FunctionNode, pkg: Package, memo, stack,
                 depth: int = 0):
    """Context-insensitive schedule summary of a function.

    Memoized ONLY when the computation was not truncated by a cycle or the
    depth bound: a truncated schedule depends on what was on the recursion
    stack at the time, and caching it would silently hide collectives in
    every later (non-cyclic) context — suppressing real HVD108 findings.
    """
    if fn.qname in memo:
        return memo[fn.qname]
    if fn.qname in stack or depth > _MAX_SCHEDULE_DEPTH:
        memo[_TRUNCATED] = memo.get(_TRUNCATED, 0) + 1
        return ("cycle", fn.qname)
    if fn.node is None:
        return ("seq",)
    import ast
    body = fn.node.body if isinstance(
        fn.node, (ast.FunctionDef, ast.AsyncFunctionDef, ast.Module)) else []
    stack = stack | {fn.qname}
    before = memo.get(_TRUNCATED, 0)
    sched = _prune(_schedule_stmts(body, fn, pkg, memo, stack, [], depth,
                                   collect=False))
    if memo.get(_TRUNCATED, 0) == before:
        memo[fn.qname] = sched         # context-free: safe to reuse
    return sched


def _render_schedule(sched, limit: int = 6) -> str:
    if sched is None:
        return "(no collectives)"
    ops: List[str] = []

    def walk(node):
        if not isinstance(node, tuple) or not node:
            return
        if node[0] == "op":
            ops.append(node[1])
        elif node[0] == "seq":
            for item in node[1:]:
                walk(item)
        elif node[0] == "branch":
            ops.append("{" + _render_schedule(node[1], limit) + " | "
                       + _render_schedule(node[2], limit) + "}")
        elif node[0] == "loop":
            ops.append("loop[" + _render_schedule(node[1], limit) + "]")
        elif node[0] == "cycle":
            ops.append("…")

    walk(sched)
    if not ops:
        return "(no collectives)"
    if len(ops) > limit:
        ops = ops[:limit] + ["…"]
    return ", ".join(ops)


def _schedule_hvd108(pkg: Package) -> List[Finding]:
    import ast
    findings: List[Finding] = []
    memo: Dict = {}
    seen: Set[Tuple[str, int]] = set()
    for fn in pkg.iter_functions():
        if fn.node is None:
            continue
        divergences: List = []
        body = fn.node.body if isinstance(
            fn.node, (ast.FunctionDef, ast.AsyncFunctionDef, ast.Module)) \
            else []
        _schedule_stmts(body, fn, pkg, memo, {fn.qname}, divergences, 0,
                        collect=True)
        for owner, line, a, b in divergences:
            key = (owner.module.path, line)
            if key in seen or _suppressed(owner.module, line, "HVD108"):
                continue
            seen.add(key)
            findings.append(Finding(
                rule="HVD108", path=owner.module.path, line=line, col=1,
                message=(
                    f"the if/else branches at line {line} of "
                    f"{owner.name}() emit different collective schedules: "
                    f"[{_render_schedule(a)}] vs [{_render_schedule(b)}] — "
                    f"ranks taking different branches negotiate different "
                    f"sequences")))
    return findings


# ---------------------------------------------------------------------------
# HVD109: collectives reachable from transition callbacks
# ---------------------------------------------------------------------------

def _callback_hvd109(pkg: Package) -> List[Finding]:
    findings: List[Finding] = []
    seen: Set[Tuple[str, int]] = set()
    for fn in pkg.iter_functions():
        if not fn.is_callback:
            continue
        targets: List[Tuple[FunctionNode, Tuple[CallSite, ...]]] = \
            [(fn, ())] + list(reachable(fn, max_depth=_MAX_CHAIN))
        for target, chain in targets:
            for col in target.collectives:
                key = (target.module.path, col.line)
                if key in seen or \
                        _suppressed(target.module, col.line, "HVD109"):
                    continue
                seen.add(key)
                what = ("sharded optimizer update (schedules "
                        "reducescatter[sharded] + allgather[sharded])"
                        if col.name == "sharded_update" else
                        f"collective {col.name!r}")
                findings.append(Finding(
                    rule="HVD109", path=target.module.path, line=col.line,
                    col=col.col,
                    message=(
                        f"{what} is reachable from "
                        f"elastic-transition callback {fn.name!r} "
                        f"({fn.module.base}:{fn.lineno}"
                        + (f", via {_chain_str(fn, chain, target)}"
                           if chain else "")
                        + ") — the rank set is mid-transition there; "
                          "peers may already have left")))
    return findings


# ---------------------------------------------------------------------------
# Entry points
# ---------------------------------------------------------------------------

def analyze_package(paths: Sequence[str],
                    package: Optional[Package] = None) -> List[Finding]:
    """Whole-package analysis: per-module lint + interprocedural passes.

    Returns findings sorted by (path, line, rule).  Per-module HVD103
    findings refuted by cross-module facts (the broadcast lives in a
    helper module) are dropped — whole-package mode is strictly more
    precise in both directions.
    """
    pkg = package or build_package(paths)
    findings: List[Finding] = []
    from .collective_lint import iter_python_files, lint_source
    by_path = {m.path: m for m in pkg.all_modules}
    for f in iter_python_files(paths):
        ap = os.path.abspath(f)
        mod = by_path.get(ap)
        # Pass 1 already read+parsed every parseable module — lint its
        # retained source instead of re-reading; files pass 1 skipped
        # (syntax errors) still go through lint_file for their HVD100.
        per_module = lint_source(mod.source, ap) if mod is not None \
            else lint_file(f)
        for finding in per_module:
            if finding.rule == "HVD103":
                continue    # recomputed over closures below, both verdicts
            finding.path = os.path.abspath(finding.path)
            findings.append(finding)
    findings += _interprocedural_hvd101(pkg)
    findings += _closure_facts_hvd102_103(pkg)
    findings += _schedule_hvd108(pkg)
    findings += _callback_hvd109(pkg)
    uniq: Dict[Tuple[str, str, int, int], Finding] = {}
    for f in findings:
        uniq.setdefault((f.rule, os.path.abspath(f.path), f.line, f.col), f)
    return sorted(uniq.values(), key=lambda f: (f.path, f.line, f.col,
                                                f.rule))


def build_static_index(paths: Sequence[str],
                       package: Optional[Package] = None,
                       findings: Optional[List[Finding]] = None) -> Dict:
    """Map ``basename:line`` call sites → static call-graph nodes + the
    rules flagged there.  The runtime sanitizer keys its ledger sites the
    same way (``HVD_TPU_SANITIZER_STATIC_INDEX``), so a runtime divergence
    report can name the static finding that would have caught it."""
    pkg = package or build_package(paths)
    if findings is None:
        findings = analyze_package(paths, package=pkg)
    rules_by_site: Dict[str, List[str]] = {}
    for f in findings:
        site = f"{os.path.basename(f.path)}:{f.line}"
        rules = rules_by_site.setdefault(site, [])
        if f.rule not in rules:
            rules.append(f.rule)
    sites: Dict[str, Dict] = {}
    for fn in pkg.iter_functions():
        for i, col in enumerate(fn.collectives):
            site = f"{fn.module.base}:{col.line}"
            sites[site] = {
                "node": fn.qname,
                "op": col.name,
                "index": i,
                "guarded": col.guard is not None,
                "rules": rules_by_site.get(site, []),
            }
    return {"version": 1, "sites": sites}
