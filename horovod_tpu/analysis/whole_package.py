"""Pass 2 of the whole-package analyzer: interprocedural fact propagation.

Runs on the symbol table + call graph from :mod:`.callgraph` and closes the
gaps per-module lint cannot see:

- **HVD101, interprocedural** — a collective inside a helper that is only
  *called* from a rank-guarded branch (possibly across modules, through
  aliases/partials/methods) is flagged at the collective site with the
  guarded call chain spelled out.  Context-bounded: a helper called from
  both guarded and unguarded sites reports only the guarded path — the
  guard context travels along each chain instead of being merged into the
  callee.
- **HVD102/HVD103, cross-module** — process-set registration and
  initial-broadcast facts are unioned over each entry point's call-graph
  closure.  A training script whose ``broadcast_parameters`` lives in a
  helper module stops false-positiving; one whose ``init()`` and
  ``DistributedOptimizer`` are split across modules starts firing.
- **HVD108** — per entry point, a *collective schedule* (the sequence of
  collectives reachable along each branch) is computed; two paths through
  one function that emit different sequences are flagged unless the branch
  condition is provably rank-invariant.
- **HVD109** — collectives reachable from elastic/churn transition
  callbacks (``on_leave``/``new_generation``/... or functions handed to
  ``register_reset_callbacks``), where the rank set is mid-transition.

``build_static_index`` exports a call-site → static-node map that the
runtime sanitizer (``HVD_TPU_SANITIZER_STATIC_INDEX``) folds into its
ledger reports, so a runtime divergence names the static finding that
would have caught it.
"""

from __future__ import annotations

import os
from typing import Dict, List, Optional, Sequence, Set, Tuple

from .callgraph import (
    CallSite, CollectiveSite, FunctionNode, ModuleInfo, Package,
    ProcessSetValue, build_package, is_uniform_test, proven_overlap,
    reachable,
)
from .collective_lint import (
    _FunctionFacts, _SYNC_CALLS, _TRAINING_WRAPPERS, _mentions_rank,
    lint_file,
)
from .findings import Finding

_MAX_CHAIN = 16          # call-graph propagation depth bound
_MAX_SCHEDULE_DEPTH = 10  # schedule splice depth bound


def _site_events(col: CollectiveSite) -> List:
    """Schedule events a collective site actually submits (ISSUE 15).

    A ``sharded_update`` site (``opt.update(...)`` on a
    ``DistributedOptimizer(sharded=...)`` / ``sharded_optimizer`` /
    ``full_sharded_optimizer`` binding) schedules the ZeRO pipeline —
    reduce-scatter then allgather, never an allreduce.  Sharded
    collectives carry the ``[sharded]`` / ``[full]`` dimension their
    fusion key / negotiation digest carries: a sharded reduce-scatter and
    an unsharded one of the same shapes are DIFFERENT programs — and the
    FSDP (ISSUE 18) pipeline's legs a third flavour again — so schedules
    comparing them must diverge.

    Every event carries the site's process-set LANE (ISSUE 16): each
    registered set is its own communicator with its own ordered stream, so
    ``allreduce@evens`` and a world ``allreduce`` are different schedule
    entries — divergence is judged per set, and HVD111 compares the
    cross-lane interleaving of overlapping sets."""
    lane = col.ps.lane
    tag = "full" if col.sharded == "full" else "sharded"
    if col.name == "sharded_update":
        return [("op", f"reducescatter[{tag}]", lane),
                ("op", f"allgather[{tag}]", lane)]
    if col.sharded:
        return [("op", f"{col.name}[{tag}]", lane)]
    if col.hierarchical:
        # Two-level dispatch pin (ISSUE 17): hierarchical= rides the
        # fusion key (never the digest), so a pinned two-level allreduce
        # and a flat one are different batch plans — a schedule dimension
        # exactly like [sharded].
        return [("op", f"{col.name}[hier]", lane)]
    return [("op", col.name, lane)]


def _suppressed(mod: ModuleInfo, line: int, rule: str) -> bool:
    ids = mod.suppressed.get(line, set())
    return "ALL" in ids or rule in ids


def _chain_str(entry: FunctionNode, chain: Sequence[CallSite],
               target: FunctionNode) -> str:
    hops = [f"{entry.module.base}:{chain[0].line}" if chain else
            entry.module.base]
    for cs in chain[1:]:
        hops.append(f"{cs.callee_expr or '?'}()")
    hops.append(f"{target.name}() [{target.module.base}:{target.lineno}]")
    return " -> ".join(hops)


# ---------------------------------------------------------------------------
# HVD101: rank-guard propagation along the call graph
# ---------------------------------------------------------------------------

def _interprocedural_hvd101(pkg: Package) -> List[Finding]:
    findings: List[Finding] = []
    best: Dict[Tuple[str, int], Tuple[int, Finding]] = {}
    for fn in pkg.iter_functions():
        for cs in fn.calls:
            if cs.guard is None or cs.resolved is None:
                continue
            # BFS from the guarded callee; the guard context belongs to
            # THIS chain only (bounded context-sensitivity): other call
            # sites of the same helper stay unguarded.
            targets = [(cs.resolved, (cs,))]
            targets += [(t, (cs,) + chain)
                        for t, chain in reachable(cs.resolved,
                                                  max_depth=_MAX_CHAIN)]
            for target, chain in targets:
                for col in target.collectives:
                    if col.guard is not None:
                        continue        # already flagged intra-procedurally
                    if _suppressed(target.module, col.line, "HVD101") or \
                            _suppressed(fn.module, cs.line, "HVD101"):
                        continue
                    key = (target.module.path, col.line)
                    f = Finding(
                        rule="HVD101", path=target.module.path,
                        line=col.line, col=col.col,
                        message=(
                            f"collective {col.name!r} is only reached "
                            f"through a rank-guarded call chain "
                            f"({cs.guard.describe(fn.module.base)}): "
                            f"{_chain_str(fn, chain, target)} — only a "
                            f"subset of ranks submits it, the rest of the "
                            f"world blocks in negotiation"))
                    prev = best.get(key)
                    if prev is None or len(chain) < prev[0]:
                        best[key] = (len(chain), f)
    findings.extend(f for _, f in best.values())
    return findings


# ---------------------------------------------------------------------------
# HVD102/HVD103: entry-closure fact flow
# ---------------------------------------------------------------------------

def _entry_roots(mod: ModuleInfo) -> List[FunctionNode]:
    """Closure roots of a module: its top level plus every function defined
    in it that no analyzed code calls (externally invokable — ``main()``
    behind an ``if __name__`` block, CLI handlers, callbacks)."""
    roots = [mod.toplevel] if mod.toplevel is not None else []
    roots += [fn for fn in mod.all_functions
              if fn is not mod.toplevel and fn.in_edges == 0]
    return roots


def _closure(mod: ModuleInfo) -> List[FunctionNode]:
    out: List[FunctionNode] = []
    seen: Set[str] = set()
    for root in _entry_roots(mod):
        if root.qname not in seen:
            seen.add(root.qname)
            out.append(root)
        for t, _chain in reachable(root, max_depth=_MAX_CHAIN):
            if t.qname not in seen:
                seen.add(t.qname)
                out.append(t)
    return out


def _closure_facts_hvd102_103(pkg: Package) -> List[Finding]:
    findings: List[Finding] = []
    for mod in pkg.all_modules:
        closure = _closure(mod)
        names: Set[str] = set()
        elastic = False
        for fn in closure:
            names |= fn.called_names
            elastic = elastic or fn.uses_elastic_state

        # HVD103 over the closure: init + gradient reduction anywhere in
        # reach, no state sync anywhere in reach.
        if "init" in names and (names & _TRAINING_WRAPPERS) \
                and not (names & _SYNC_CALLS) and not elastic:
            line = mod.first_training_line or mod.init_line or 1
            if not _suppressed(mod, line, "HVD103"):
                findings.append(Finding(
                    rule="HVD103", path=mod.path, line=line, col=1,
                    message=(
                        "entry point calls init() and reduces gradients "
                        "(directly or through its call-graph closure) but "
                        "never broadcasts initial state from rank 0; ranks "
                        "train divergent models")))

        # HVD102 cross-module: the closure registers subgroup process sets
        # somewhere, and THIS module's own code submits bare collectives.
        # (Same-module registration is per-module lint's job — skip it to
        # avoid duplicate findings.)
        own_names: Set[str] = set()
        for fn in mod.all_functions:
            own_names |= fn.called_names
        if "add_process_set" in names and "add_process_set" not in own_names:
            for fn in mod.all_functions:
                for col in fn.collectives:
                    if col.has_process_set or \
                            _suppressed(mod, col.line, "HVD102"):
                        continue
                    findings.append(Finding(
                        rule="HVD102", path=mod.path, line=col.line,
                        col=col.col,
                        message=(
                            f"collective {col.name!r} omits process_set= "
                            f"while this entry point's call-graph closure "
                            f"registers subgroup process sets (in another "
                            f"module); it targets the GLOBAL set — a "
                            f"deadlock if only subgroup members reach "
                            f"this call")))
    return findings


# ---------------------------------------------------------------------------
# HVD108: collective schedules per branch
# ---------------------------------------------------------------------------

def _terminates(stmts) -> bool:
    """A statement list that definitely leaves the enclosing suite."""
    import ast
    return bool(stmts) and isinstance(
        stmts[-1], (ast.Return, ast.Raise, ast.Continue, ast.Break))


def _schedule_stmts(stmts, fn: FunctionNode, pkg: Package, memo, stack,
                    divergences, depth: int, collect: bool):
    """Schedule of a statement list.  ``collect`` gates divergence
    recording so each function's If nodes are reported once (when the
    function itself is analyzed), not re-reported at every splice site."""
    import ast
    seq: List = []
    calls_by_line: Dict[Tuple[int, int], FunctionNode] = {}
    for cs in fn.calls:
        if cs.resolved is not None:
            calls_by_line[(cs.line, cs.col)] = cs.resolved
    cols_by_line: Dict[Tuple[int, int], CollectiveSite] = {
        (c.line, c.col): c for c in fn.collectives}

    def expr_events(node) -> List:
        # Post-order: a call's arguments are evaluated (and their
        # collectives submitted) BEFORE the call itself completes, so
        # hvd.allgather(helper(x)) must record helper's ops first.
        ev: List = []

        def rec(n):
            for child in ast.iter_child_nodes(n):
                rec(child)
            if not isinstance(n, ast.Call):
                return
            key = (n.lineno, n.col_offset + 1)
            col = cols_by_line.get(key)
            if col is not None:
                ev.extend(_site_events(col))
                return
            target = calls_by_line.get(key)
            if target is not None:
                spliced = _schedule_of(target, pkg, memo, stack, depth + 1)
                if spliced is not None:
                    ev.append(spliced)

        rec(node)
        return [e for e in ev if e not in (("seq",), None)]

    def sub_sched(sub_stmts):
        return _schedule_stmts(sub_stmts, fn, pkg, memo, stack,
                               divergences, depth, collect)

    tainted = _fn_tainted(fn)
    i, n = 0, len(stmts)
    while i < n:
        stmt = stmts[i]
        i += 1
        if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.ClassDef)):
            continue                       # defs don't run at the def site
        if isinstance(stmt, ast.If):
            seq.extend(expr_events(stmt.test))
            body_t, or_t = _terminates(stmt.body), _terminates(stmt.orelse)
            if (body_t or or_t) and i < n:
                # Guard-clause folding: a terminating arm's real
                # alternative is the FALL-THROUGH code, not the lexical
                # orelse — `if fast: return coll(x)` vs the rest of the
                # function compare as two complete paths.
                rest = stmts[i:]
                a = _prune(sub_sched(
                    list(stmt.body) + ([] if body_t else rest)))
                b = _prune(sub_sched(
                    list(stmt.orelse) + ([] if or_t else rest)))
                i = n                      # rest is folded into the arms
            else:
                a = _prune(sub_sched(stmt.body))
                b = _prune(sub_sched(stmt.orelse))
            if a == b:
                if a is not None:
                    seq.append(a)
            else:
                if collect:
                    # Classify the divergence: rank-divergent tests are
                    # HVD101's domain (so HVD108 skips them) but they ARE
                    # the classic cross-communicator interleaving (HVD111
                    # judges both kinds); provably-uniform tests diverge
                    # for no rank at all.
                    if _mentions_rank(stmt.test, tainted):
                        divergences.append((fn, stmt.lineno, a, b, "rank"))
                    elif not is_uniform_test(stmt.test, tainted,
                                             _fn_uniform_names(fn)):
                        divergences.append((fn, stmt.lineno, a, b, "data"))
                seq.append(("branch", a or ("seq",), b or ("seq",)))
        elif isinstance(stmt, (ast.For, ast.AsyncFor, ast.While)):
            if isinstance(stmt, ast.While):
                seq.extend(expr_events(stmt.test))
            else:
                seq.extend(expr_events(stmt.iter))
            body = _schedule_stmts(stmt.body, fn, pkg, memo, stack,
                                   divergences, depth, collect)
            if len(body) > 1:
                seq.append(("loop", body))
            seq.extend(_schedule_stmts(stmt.orelse, fn, pkg, memo, stack,
                                       divergences, depth, collect)[1:])
        elif isinstance(stmt, ast.Try):
            seq.extend(_schedule_stmts(stmt.body, fn, pkg, memo, stack,
                                       divergences, depth, collect)[1:])
            # handlers model exceptional divergence — deliberately ignored
            seq.extend(_schedule_stmts(stmt.finalbody, fn, pkg, memo, stack,
                                       divergences, depth, collect)[1:])
        elif isinstance(stmt, (ast.With, ast.AsyncWith)):
            for item in stmt.items:
                seq.extend(expr_events(item.context_expr))
            seq.extend(_schedule_stmts(stmt.body, fn, pkg, memo, stack,
                                       divergences, depth, collect)[1:])
        else:
            seq.extend(expr_events(stmt))
    return tuple(["seq"] + seq)


def _prune(sched):
    """Normalize a schedule: drop every structural node (seq/branch/loop)
    that contains no collective op anywhere beneath it, and flatten nested
    sequences — so two branches that differ only in collective-free
    structure compare EQUAL (both prune to None).  Cycle markers prune
    away too: an unexpanded recursive call contributes no known ops."""
    if not isinstance(sched, tuple) or not sched:
        return None
    tag = sched[0]
    if tag == "op":
        return sched
    if tag == "seq":
        flat: List = []
        for item in sched[1:]:
            p = _prune(item)
            if p is None:
                continue
            if isinstance(p, tuple) and p and p[0] == "seq":
                flat.extend(p[1:])
            else:
                flat.append(p)
        return tuple(["seq"] + flat) if flat else None
    if tag == "branch":
        a, b = _prune(sched[1]), _prune(sched[2])
        if a is None and b is None:
            return None
        if a == b:
            return a
        return ("branch", a or ("seq",), b or ("seq",))
    if tag == "loop":
        body = _prune(sched[1])
        return None if body is None else ("loop", body)
    return None        # "cycle" and anything unknown


def _fn_tainted(fn: FunctionNode) -> Set[str]:
    cached = getattr(fn, "_tainted", None)
    if cached is None:
        facts = _FunctionFacts()
        if fn.node is not None:
            facts.visit(fn.node)
        cached = fn._tainted = facts.tainted
    return cached


def _fn_uniform_names(fn: FunctionNode) -> Set[str]:
    """Names assigned from world-size-style accessors — rank-invariant by
    construction, so branches on them don't diverge the schedule."""
    from .callgraph import _UNIFORM_CALLS
    cached = getattr(fn, "_uniform_names", None)
    if cached is None:
        facts = _FunctionFacts(source_calls=_UNIFORM_CALLS)
        if fn.node is not None:
            facts.visit(fn.node)
        cached = fn._uniform_names = facts.tainted
    return cached


# Reserved memo key counting cycle/depth truncations ("::" can't appear in
# a function qname, so it never collides with one).
_TRUNCATED = "::truncated::"


def _schedule_of(fn: FunctionNode, pkg: Package, memo, stack,
                 depth: int = 0):
    """Context-insensitive schedule summary of a function.

    Memoized ONLY when the computation was not truncated by a cycle or the
    depth bound: a truncated schedule depends on what was on the recursion
    stack at the time, and caching it would silently hide collectives in
    every later (non-cyclic) context — suppressing real HVD108 findings.
    """
    if fn.qname in memo:
        return memo[fn.qname]
    if fn.qname in stack or depth > _MAX_SCHEDULE_DEPTH:
        memo[_TRUNCATED] = memo.get(_TRUNCATED, 0) + 1
        return ("cycle", fn.qname)
    if fn.node is None:
        return ("seq",)
    import ast
    body = fn.node.body if isinstance(
        fn.node, (ast.FunctionDef, ast.AsyncFunctionDef, ast.Module)) else []
    stack = stack | {fn.qname}
    before = memo.get(_TRUNCATED, 0)
    sched = _prune(_schedule_stmts(body, fn, pkg, memo, stack, [], depth,
                                   collect=False))
    if memo.get(_TRUNCATED, 0) == before:
        memo[fn.qname] = sched         # context-free: safe to reuse
    return sched


def _render_schedule(sched, limit: int = 6) -> str:
    if sched is None:
        return "(no collectives)"
    ops: List[str] = []

    def walk(node):
        if not isinstance(node, tuple) or not node:
            return
        if node[0] == "op":
            lane = node[2] if len(node) > 2 else "world"
            ops.append(node[1] if lane == "world"
                       else f"{node[1]}@{lane}")
        elif node[0] == "seq":
            for item in node[1:]:
                walk(item)
        elif node[0] == "branch":
            ops.append("{" + _render_schedule(node[1], limit) + " | "
                       + _render_schedule(node[2], limit) + "}")
        elif node[0] == "loop":
            ops.append("loop[" + _render_schedule(node[1], limit) + "]")
        elif node[0] == "cycle":
            ops.append("…")

    walk(sched)
    if not ops:
        return "(no collectives)"
    if len(ops) > limit:
        ops = ops[:limit] + ["…"]
    return ", ".join(ops)


def _collect_divergences(pkg: Package) -> List:
    """All branch divergences in the package as ``(fn, line, a, b, kind)``
    with kind ``"data"`` (HVD108's domain) or ``"rank"`` (HVD101's domain,
    but HVD111-eligible: a rank-divergent branch is exactly how ranks end
    up submitting different cross-set interleavings)."""
    import ast
    memo: Dict = {}
    divergences: List = []
    for fn in pkg.iter_functions():
        if fn.node is None:
            continue
        body = fn.node.body if isinstance(
            fn.node, (ast.FunctionDef, ast.AsyncFunctionDef, ast.Module)) \
            else []
        _schedule_stmts(body, fn, pkg, memo, {fn.qname}, divergences, 0,
                        collect=True)
    return divergences


def _schedule_hvd108(divergences: List) -> List[Finding]:
    findings: List[Finding] = []
    seen: Set[Tuple[str, int]] = set()
    for owner, line, a, b, kind in divergences:
        if kind != "data":
            continue
        key = (owner.module.path, line)
        if key in seen or _suppressed(owner.module, line, "HVD108"):
            continue
        seen.add(key)
        findings.append(Finding(
            rule="HVD108", path=owner.module.path, line=line, col=1,
            message=(
                f"the if/else branches at line {line} of "
                f"{owner.name}() emit different collective schedules: "
                f"[{_render_schedule(a)}] vs [{_render_schedule(b)}] — "
                f"ranks taking different branches negotiate different "
                f"sequences")))
    return findings


# ---------------------------------------------------------------------------
# HVD111: branch-divergent interleaving of overlapping process sets
# ---------------------------------------------------------------------------

def _flat_ops(sched) -> List[Tuple[str, str]]:
    """Flatten a schedule to its ``(op, lane)`` submission stream.  Branch
    arms are included in order (a then b) — deterministic, and identical
    sub-branches contribute identically to both outer arms."""
    out: List[Tuple[str, str]] = []

    def walk(node):
        if not isinstance(node, tuple) or not node:
            return
        if node[0] == "op":
            out.append((node[1], node[2] if len(node) > 2 else "world"))
        elif node[0] in ("seq", "branch"):
            for item in node[1:]:
                walk(item)
        elif node[0] == "loop":
            walk(node[1])

    walk(sched)
    return out


def _lane_values(pkg: Package) -> Dict[str, ProcessSetValue]:
    vals: Dict[str, ProcessSetValue] = {}
    for fn in pkg.iter_functions():
        for col in fn.collectives:
            vals.setdefault(col.ps.lane, col.ps)
    return vals


def _schedule_hvd111(divergences: List, pkg: Package) -> List[Finding]:
    """The cross-communicator deadlock: two branch arms interleave
    collectives over two PROVEN-overlapping process sets differently.
    Each set's own lane can even be self-consistent — but the shared
    ranks execute submissions in program order, so arm A holds set-1's
    slot while waiting on set-2 and arm B the reverse."""
    import itertools
    lane_vals = _lane_values(pkg)
    findings: List[Finding] = []
    seen: Set[Tuple] = set()
    for owner, line, a, b, _kind in divergences:
        fa, fb = _flat_ops(a), _flat_ops(b)
        lanes = sorted({lane for _, lane in fa + fb})
        for l1, l2 in itertools.combinations(lanes, 2):
            v1, v2 = lane_vals.get(l1), lane_vals.get(l2)
            if v1 is None or v2 is None or not proven_overlap(v1, v2):
                continue
            pa = [(op, ln) for op, ln in fa if ln in (l1, l2)]
            pb = [(op, ln) for op, ln in fb if ln in (l1, l2)]
            if pa == pb or not pa or not pb:
                continue
            # An actual interleaving requires one arm to touch BOTH lanes;
            # one-sided pairs are HVD101/HVD108's territory.
            if not any({ln for _, ln in p} == {l1, l2} for p in (pa, pb)):
                continue
            key = (owner.module.path, line, l1, l2)
            if key in seen or _suppressed(owner.module, line, "HVD111"):
                continue
            seen.add(key)
            related = [(owner.module.path, c.line)
                       for c in owner.collectives if c.ps.lane in (l1, l2)]

            def _fmt(p):
                return ", ".join(op if ln == "world" else f"{op}@{ln}"
                                 for op, ln in p)

            findings.append(Finding(
                rule="HVD111", path=owner.module.path, line=line, col=1,
                message=(
                    f"the branches at line {line} of {owner.name}() submit "
                    f"collectives over OVERLAPPING process sets "
                    f"({v1.describe()} and {v2.describe()}) in different "
                    f"interleavings: [{_fmt(pa)}] vs [{_fmt(pb)}] — ranks "
                    f"shared by both sets hold one communicator's slot "
                    f"while waiting on the other: cross-communicator "
                    f"deadlock"),
                process_set=f"{v1.lane} | {v2.lane}",
                related=related or None))
    return findings


# ---------------------------------------------------------------------------
# HVD113: world collective reachable from a process-set-scoped region
# ---------------------------------------------------------------------------

def _is_bare_world(col: CollectiveSite) -> bool:
    return not col.has_process_set and col.ps.kind == "world"


def _hvd113(pkg: Package) -> List[Finding]:
    findings: List[Finding] = []
    best: Dict[Tuple[str, int], Tuple[int, Finding]] = {}

    # (a) Interprocedural: a call site binds a concrete registered set into
    # a helper (process_set=<named>, directly or pinned via partial) whose
    # closure contains hard-coded world collectives.  Callees that FORWARD
    # a process_set to a site don't trip it — that's the clean pattern.
    for fn in pkg.iter_functions():
        for cs in fn.calls:
            v = cs.ps_kwarg
            if v is None or v.kind != "named" or cs.resolved is None:
                continue
            targets = [(cs.resolved, (cs,))]
            targets += [(t, (cs,) + chain)
                        for t, chain in reachable(cs.resolved,
                                                  max_depth=_MAX_CHAIN)]
            for target, chain in targets:
                for col in target.collectives:
                    if not _is_bare_world(col):
                        continue
                    if _suppressed(target.module, col.line, "HVD113") or \
                            _suppressed(fn.module, cs.line, "HVD113"):
                        continue
                    key = (target.module.path, col.line)
                    f = Finding(
                        rule="HVD113", path=target.module.path,
                        line=col.line, col=col.col,
                        message=(
                            f"collective {col.name!r} hard-codes the WORLD "
                            f"set but is reached from a region scoped to "
                            f"{v.describe()} "
                            f"(process_set= bound at {fn.module.base}:"
                            f"{cs.line}, {_chain_str(fn, chain, target)}) "
                            f"— only the set's members run this region, "
                            f"so the world collective waits on ranks that "
                            f"never arrive (tenant-leak)"),
                        chain=[_chain_str(fn, chain, target)],
                        process_set=v.lane,
                        related=[(fn.module.path, cs.line)])
                    prev = best.get(key)
                    if prev is None or len(chain) < prev[0]:
                        best[key] = (len(chain), f)
    findings.extend(f for _, f in best.values())

    # (b) Intra-function: a helper that takes a process set and scopes at
    # least one collective with it (``process_set=<param>``, or forwarding
    # the param positionally) leaks if another collective in the same body
    # silently targets the world.
    for fn in pkg.iter_functions():
        scoped = [c for c in fn.collectives if c.ps.kind == "param"]
        if not scoped:
            continue
        for col in fn.collectives:
            if not _is_bare_world(col):
                continue
            if _suppressed(fn.module, col.line, "HVD113"):
                continue
            key = (fn.module.path, col.line)
            if key in best:
                continue
            v = scoped[0].ps
            findings.append(Finding(
                rule="HVD113", path=fn.module.path, line=col.line,
                col=col.col,
                message=(
                    f"collective {col.name!r} hard-codes the WORLD set "
                    f"inside {fn.name}(), which scopes its other "
                    f"collectives to {v.describe()} — when a caller binds "
                    f"a subgroup, only its members reach this line and "
                    f"the world collective deadlocks (tenant-leak)"),
                process_set=v.lane,
                related=[(fn.module.path, c.line) for c in scoped]))
    return findings


# ---------------------------------------------------------------------------
# HVD114: overlapping sets interleaved with no dominating order edge
# ---------------------------------------------------------------------------

def _suite_streams(fn: FunctionNode):
    """Yield ``(stream, in_loop)`` per straight-line suite of ``fn``:
    the function body, each branch arm, each loop/try/with body — WITHOUT
    mixing arms of one If into a single stream (they never execute
    together).  ``stream`` is the suite's direct collective sites in
    source order (nested control-flow suites are yielded separately)."""
    import ast
    if fn.node is None:
        return
    by_pos = {(c.line, c.col): c for c in fn.collectives}

    def direct_sites(stmt) -> List[CollectiveSite]:
        out = []
        for n in ast.walk(stmt):
            if isinstance(n, ast.Call):
                col = by_pos.get((n.lineno, n.col_offset + 1))
                if col is not None:
                    out.append(col)
        return sorted(out, key=lambda c: (c.line, c.col))

    def suites(body, in_loop):
        stream: List[CollectiveSite] = []
        for stmt in body:
            if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef,
                                 ast.ClassDef)):
                continue
            if isinstance(stmt, ast.If):
                stream.extend(direct_sites(stmt.test))
                yield from suites(stmt.body, in_loop)
                yield from suites(stmt.orelse, in_loop)
            elif isinstance(stmt, (ast.For, ast.AsyncFor, ast.While)):
                yield from suites(stmt.body, True)
                yield from suites(stmt.orelse, in_loop)
            elif isinstance(stmt, ast.Try):
                yield from suites(stmt.body, in_loop)
                for h in stmt.handlers:
                    yield from suites(h.body, in_loop)
                yield from suites(stmt.orelse, in_loop)
                yield from suites(stmt.finalbody, in_loop)
            elif isinstance(stmt, (ast.With, ast.AsyncWith)):
                yield from suites(stmt.body, in_loop)
            else:
                stream.extend(direct_sites(stmt))
        yield stream, in_loop

    body = fn.node.body if isinstance(
        fn.node, (ast.FunctionDef, ast.AsyncFunctionDef, ast.Module)) \
        else []
    yield from suites(body, False)


def _is_order_edge(col: CollectiveSite) -> bool:
    """A world-level barrier (or world synchronize) between two lanes
    dominates both sets' streams: everything before it on every member
    rank completes before anything after — the order edge HVD114 wants."""
    return col.ps.kind == "world" and (
        "barrier" in col.name or col.name == "synchronize")


def _hvd114(pkg: Package) -> List[Finding]:
    findings: List[Finding] = []
    seen: Set[Tuple[str, int]] = set()

    def emit(fn, site, a: CollectiveSite, b: CollectiveSite, looped: bool):
        key = (fn.module.path, site.line)
        if key in seen or _suppressed(fn.module, site.line, "HVD114"):
            return
        seen.add(key)
        findings.append(Finding(
            rule="HVD114", path=fn.module.path, line=site.line,
            col=site.col,
            message=(
                f"{fn.name}() alternates submissions between overlapping "
                f"process sets ({a.ps.describe()} and {b.ps.describe()}"
                + (", across loop iterations" if looped else "")
                + ") with no world barrier between the lanes — nothing "
                  "establishes a dominating order edge, so scheduling "
                  "skew on the shared ranks can entangle the two "
                  "streams"),
            process_set=f"{a.ps.lane} | {b.ps.lane}"))

    for fn in pkg.iter_functions():
        for stream, in_loop in _suite_streams(fn):
            n = len(stream)
            if n < 2:
                continue
            # Straight-line alternation A ... B ... A with no world
            # barrier anywhere between the first and last leg.
            for k in range(n):
                ck = stream[k]
                if _is_order_edge(ck):
                    continue
                for j in range(k):
                    cj = stream[j]
                    if cj.ps.lane == ck.ps.lane or \
                            not proven_overlap(cj.ps, ck.ps):
                        continue
                    for i in range(j):
                        ci = stream[i]
                        if ci.ps.lane != ck.ps.lane or _is_order_edge(ci):
                            continue
                        if any(_is_order_edge(c)
                               for c in stream[i + 1:k]):
                            continue
                        emit(fn, ck, cj, ck, looped=False)
                        break
            # A loop body touching two overlapping lanes alternates by
            # construction (iteration N's tail meets iteration N+1's
            # head) unless an order edge sits somewhere in the body.
            if in_loop and not any(_is_order_edge(c) for c in stream):
                for j in range(n):
                    for i in range(j):
                        if stream[i].ps.lane != stream[j].ps.lane and \
                                proven_overlap(stream[i].ps,
                                               stream[j].ps):
                            emit(fn, stream[j], stream[i], stream[j],
                                 looped=True)
    return findings


# ---------------------------------------------------------------------------
# HVD109: collectives reachable from transition callbacks
# ---------------------------------------------------------------------------

def _callback_hvd109(pkg: Package) -> List[Finding]:
    findings: List[Finding] = []
    seen: Set[Tuple[str, int]] = set()
    for fn in pkg.iter_functions():
        if not fn.is_callback:
            continue
        targets: List[Tuple[FunctionNode, Tuple[CallSite, ...]]] = \
            [(fn, ())] + list(reachable(fn, max_depth=_MAX_CHAIN))
        for target, chain in targets:
            for col in target.collectives:
                key = (target.module.path, col.line)
                if key in seen or \
                        _suppressed(target.module, col.line, "HVD109"):
                    continue
                seen.add(key)
                tag = "full" if col.sharded == "full" else "sharded"
                what = (f"sharded optimizer update (schedules "
                        f"reducescatter[{tag}] + allgather[{tag}])"
                        if col.name == "sharded_update" else
                        f"collective {col.name!r}")
                if col.ps.kind != "world":
                    what += f" over {col.ps.describe()}"
                findings.append(Finding(
                    rule="HVD109", path=target.module.path, line=col.line,
                    col=col.col,
                    message=(
                        f"{what} is reachable from "
                        f"elastic-transition callback {fn.name!r} "
                        f"({fn.module.base}:{fn.lineno}"
                        + (f", via {_chain_str(fn, chain, target)}"
                           if chain else "")
                        + ") — the rank set is mid-transition there; "
                          "peers may already have left")))
    return findings


# ---------------------------------------------------------------------------
# Entry points
# ---------------------------------------------------------------------------

def analyze_package(paths: Sequence[str],
                    package: Optional[Package] = None) -> List[Finding]:
    """Whole-package analysis: per-module lint + interprocedural passes.

    Returns findings sorted by (path, line, rule).  Per-module HVD103
    findings refuted by cross-module facts (the broadcast lives in a
    helper module) are dropped — whole-package mode is strictly more
    precise in both directions.
    """
    pkg = package or build_package(paths)
    findings: List[Finding] = []
    from .collective_lint import iter_python_files, lint_source
    by_path = {m.path: m for m in pkg.all_modules}
    for f in iter_python_files(paths):
        ap = os.path.abspath(f)
        mod = by_path.get(ap)
        # Pass 1 already read+parsed every parseable module — lint its
        # retained source instead of re-reading; files pass 1 skipped
        # (syntax errors) still go through lint_file for their HVD100.
        per_module = lint_source(mod.source, ap) if mod is not None \
            else lint_file(f)
        for finding in per_module:
            if finding.rule == "HVD103":
                continue    # recomputed over closures below, both verdicts
            finding.path = os.path.abspath(finding.path)
            findings.append(finding)
    findings += _interprocedural_hvd101(pkg)
    findings += _closure_facts_hvd102_103(pkg)
    divergences = _collect_divergences(pkg)
    findings += _schedule_hvd108(divergences)
    findings += _schedule_hvd111(divergences, pkg)
    findings += _hvd113(pkg)
    findings += _hvd114(pkg)
    findings += _callback_hvd109(pkg)
    uniq: Dict[Tuple[str, str, int, int], Finding] = {}
    for f in findings:
        uniq.setdefault((f.rule, os.path.abspath(f.path), f.line, f.col), f)
    return sorted(uniq.values(), key=lambda f: (f.path, f.line, f.col,
                                                f.rule))


def build_static_index(paths: Sequence[str],
                       package: Optional[Package] = None,
                       findings: Optional[List[Finding]] = None) -> Dict:
    """Map ``basename:line`` call sites → static call-graph nodes + the
    rules flagged there.  The runtime sanitizer keys its ledger sites the
    same way (``HVD_TPU_SANITIZER_STATIC_INDEX``), so a runtime divergence
    report can name the static finding that would have caught it."""
    pkg = package or build_package(paths)
    if findings is None:
        findings = analyze_package(paths, package=pkg)
    rules_by_site: Dict[str, List[str]] = {}
    for f in findings:
        anchors = [(f.path, f.line)] + list(f.related or [])
        for path, line in anchors:
            site = f"{os.path.basename(path)}:{line}"
            rules = rules_by_site.setdefault(site, [])
            if f.rule not in rules:
                rules.append(f.rule)
    sites: Dict[str, Dict] = {}
    for fn in pkg.iter_functions():
        for i, col in enumerate(fn.collectives):
            site = f"{fn.module.base}:{col.line}"
            sites[site] = {
                "node": fn.qname,
                "op": col.name,
                "index": i,
                "guarded": col.guard is not None,
                "process_set": col.ps.lane,
                "rules": rules_by_site.get(site, []),
            }
    return {"version": 1, "sites": sites}
