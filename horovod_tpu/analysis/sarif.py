"""SARIF 2.1.0 emitter for analyzer findings.

SARIF (Static Analysis Results Interchange Format, OASIS 2.1.0) is what CI
annotation surfaces (GitHub code scanning, most SARIF viewers) ingest.  One
``run`` per invocation: the tool driver carries the rule catalog for every
rule that fired (id, descriptions, default level), each finding becomes a
``result`` with a physical location whose URI is repo-relative when a root
is given.

Pure stdlib — the emitter builds a plain dict; ``write_sarif`` serializes
it.  ``tests/test_whole_package.py`` validates the output against the
2.1.0 schema's structural requirements.
"""

from __future__ import annotations

import json
from typing import Dict, Iterable, List, Optional

from .findings import Finding, RULES, Severity

SARIF_VERSION = "2.1.0"
SARIF_SCHEMA = (
    "https://raw.githubusercontent.com/oasis-tcs/sarif-spec/master/"
    "Schemata/sarif-schema-2.1.0.json")

_LEVELS = {Severity.ERROR: "error", Severity.WARNING: "warning"}


def _uri(path: str, root: Optional[str]) -> str:
    # One normalization for the whole analyzer: SARIF URIs and baseline
    # keys must agree on the spelling of a finding's path, or baselined
    # findings reappear as "new" in the SARIF feed.
    from .baseline import _rel
    return _rel(path, root).lstrip("/")


def to_sarif(findings: Iterable[Finding], root: Optional[str] = None,
             tool_version: str = "0.1.0") -> Dict:
    """Render findings as a SARIF 2.1.0 log dict."""
    findings = list(findings)
    rule_ids: List[str] = []
    for f in findings:
        if f.rule not in rule_ids:
            rule_ids.append(f.rule)
    rule_ids.sort()
    rule_index = {rid: i for i, rid in enumerate(rule_ids)}

    rules = []
    for rid in rule_ids:
        r = RULES.get(rid)
        rules.append({
            "id": rid,
            "shortDescription": {"text": r.title if r else rid},
            "fullDescription": {"text": r.rationale if r else rid},
            "help": {"text": r.fix_hint if r else ""},
            "defaultConfiguration": {
                "level": _LEVELS.get(r.severity, "warning") if r
                         else "warning"},
        })

    results = []
    for f in findings:
        result = {
            "ruleId": f.rule,
            "ruleIndex": rule_index[f.rule],
            "level": _LEVELS.get(f.severity, "warning"),
            "message": {"text": f.message},
            "locations": [{
                "physicalLocation": {
                    "artifactLocation": {"uri": _uri(f.path, root)},
                    "region": {"startLine": max(1, f.line),
                               "startColumn": max(1, f.col)},
                },
            }],
        }
        props = {}
        if getattr(f, "process_set", None):
            # Resolved process-set value(s) behind the finding — lets a
            # SARIF viewer group multi-tenant findings per set.
            props["processSet"] = f.process_set
        if getattr(f, "chain", None):
            props["callChain"] = list(f.chain)
        if props:
            result["properties"] = props
        results.append(result)

    return {
        "$schema": SARIF_SCHEMA,
        "version": SARIF_VERSION,
        "runs": [{
            "tool": {"driver": {
                "name": "hvd-lint",
                "informationUri":
                    "https://github.com/horovod/horovod",
                "version": tool_version,
                "rules": rules,
            }},
            "results": results,
            "columnKind": "unicodeCodePoints",
        }],
    }


def write_sarif(findings: Iterable[Finding], path: str,
                root: Optional[str] = None) -> None:
    with open(path, "w", encoding="utf-8") as fh:
        json.dump(to_sarif(findings, root=root), fh, indent=2, sort_keys=True)
        fh.write("\n")
