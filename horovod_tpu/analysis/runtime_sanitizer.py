"""Opt-in runtime collective sanitizer (``HVD_TPU_SANITIZER=1``).

The dynamic third layer of the analyzer: what the linter and trace checker
cannot see (data-dependent branches, order decided at run time) is caught
here, the way the reference's message-table negotiation catches it — but
with *call-site attribution*.

Mechanism:

- Every entry submitted through the engine (``ops/eager.py`` →
  ``ops/engine.py`` ``enqueue_group``) is recorded in a bounded per-rank
  **ledger**: sequence number, wire name, signature digest, and the user
  call site that issued it (first stack frame outside horovod_tpu).
  The ledger is **namespaced per process set**: sequence numbers count
  within each set, and every entry lands both in the combined stream and
  in a per-set view (``ledgers[ps]``) — so one tenant's divergence is
  reported against ITS submissions (``render_tail(process_set=...)``)
  without another set's interleaved traffic muddying the tail.
- Each entry is stamped with a ``sanitizer_tag``
  (``seq=<process_set>:<i>;site=<f:l>``)
  which the controller sends BESIDE its step-invariant negotiation digest
  (the announce's separate tag field on the full path; the sparse
  slot/tag side-channel next to the bitvector on the response-cache fast
  path — ``common/controller.py _round``).  The rank-0 server folds the
  tag back into its effective-digest comparison, so two ranks submitting
  different collectives — or the same ones in a different order, or from
  different call sites — under one negotiated name produce a mismatch,
  and the existing per-tensor NegotiationError names the divergent ranks
  AND both call sites.  Keeping the tag out of the digest itself means
  the response-cache slot key stays valid across steps: sanitizer runs
  keep the steady-state fast path (docs/performance.md).
- The engine's stall inspector is tightened to
  ``HVD_TPU_SANITIZER_TIMEOUT`` seconds (default 30) and, when a stall
  fires, the report carries the ledger tail so the laggard ranks' last
  submissions (with call sites) are visible next to the stuck tensor.

- **Content-hash mode** (``HVD_TPU_SANITIZER=hash``): additionally folds a
  device→host content digest of each entry's local contribution into the
  tag (``;h=<16hex>``), closing the same-site blind spot — two ranks
  submitting divergent *data* through one call site and sequence (e.g. a
  loop over differently-ordered lists of same-shaped tensors under
  auto-names) match on every structural field, and only the content can
  tell them apart.  The check compares LOCAL contributions across ranks,
  so it is sound exactly where contributions are expected replicated
  (hyperparameters, schedules, reproduction runs with mirrored data);
  ordinary data-parallel gradients legitimately differ per rank and will
  flag — hash mode is a targeted debugging tool, not a production mode
  (docs/analysis.md "content-hash mode").
- With the monitor subsystem on (``HOROVOD_MONITOR=1``), HVD302 stall
  reports also quote the *laggard ranks'* ledger tails, pulled from the
  cross-rank aggregation table (``horovod_tpu.monitor``,
  docs/monitoring.md) — the stalling rank no longer has to ssh into the
  peer's logs to see what it last submitted.

- **Static linkage** (``HVD_TPU_SANITIZER_STATIC_INDEX=file``): the
  whole-package analyzer exports a call-site → call-graph-node map
  (``python -m horovod_tpu.analysis --whole-package --emit-static-index``).
  When set, every ledger line and HVD301/HVD302 report annotates the
  divergent call site with its static node (``mod:fn``, schedule index)
  and, when the static analysis flagged that site, the rule that would
  have caught the divergence before launch — closing the loop between the
  runtime ledger and the static collective schedule.

Env vars:
  HVD_TPU_SANITIZER=1          enable (tag mode)
  HVD_TPU_SANITIZER=hash       enable + content-hash the local contribution
  HVD_TPU_SANITIZER_TIMEOUT=s  stall warn threshold (default 30)
  HVD_TPU_SANITIZER_LEDGER=n   ledger capacity (default 512)
  HVD_TPU_SANITIZER_STATIC_INDEX=f  static call-graph index (JSON) to
                               annotate ledger reports with
"""

from __future__ import annotations

import collections
import dataclasses
import json
import os
import threading
import traceback
from typing import Deque, Dict, List, Optional, Sequence

from .findings import is_package_frame
from ..utils.logging import get_logger

log = get_logger()


def mode() -> Optional[str]:
    """``"tag"`` (HVD_TPU_SANITIZER=1), ``"hash"`` (=hash — tag plus a
    device→host content digest of the local contribution), or None."""
    v = os.environ.get("HVD_TPU_SANITIZER", "").strip().lower()
    if v in ("1", "true", "on", "yes"):
        return "tag"
    if v == "hash":
        return "hash"
    return None


def enabled() -> bool:
    return mode() is not None


@dataclasses.dataclass(frozen=True)
class LedgerEntry:
    seq: int
    name: str
    digest: str
    site: str
    # Which process set the entry was submitted under (0 = world).  The
    # seq above counts WITHIN this set — the namespace that keeps one
    # tenant's divergence report from perturbing another's stream.
    process_set: int = 0

    def render(self) -> str:
        head = f"#{self.seq}" if self.process_set == 0 \
            else f"#{self.process_set}:{self.seq}"
        return f"{head} {self.name} [{self.digest}] at {self.site}"


def _caller_site() -> str:
    """First stack frame outside the horovod_tpu package — the user call
    that issued the collective (``findings.is_package_frame`` decides what
    counts as package code).  Basename only, so the tag (which rides the
    negotiation digest) matches across ranks with different install
    paths."""
    for frame in reversed(traceback.extract_stack()):
        if not is_package_frame(frame.filename):
            return f"{os.path.basename(frame.filename)}:{frame.lineno}"
    return "<internal>"


class StaticIndex:
    """Call-site → static call-graph node map, produced by
    ``python -m horovod_tpu.analysis --whole-package --emit-static-index``.
    Sites are keyed ``basename:line`` — the same spelling
    :func:`_caller_site` stamps into ledger entries, so lookup is a dict
    hit on the hot path's *reporting* side only (never on submission)."""

    def __init__(self, sites: Dict[str, Dict]):
        self._sites = sites

    @classmethod
    def load(cls, path: str) -> Optional["StaticIndex"]:
        try:
            with open(path, "r", encoding="utf-8") as fh:
                data = json.load(fh)
            return cls(data.get("sites", {}))
        except (OSError, ValueError) as e:
            log.warning("sanitizer: cannot load static index %s: %s",
                        path, e)
            return None

    def annotate(self, site: str) -> str:
        rec = self._sites.get(site)
        if rec is None:
            return ""
        s = f" [static: {rec.get('node', '?')} #{rec.get('index', '?')}"
        ps = rec.get("process_set")
        if ps and ps != "world":
            s += f" over {ps}"
        rules = rec.get("rules")
        if rules:
            s += f"; {'/'.join(rules)} flagged this site statically"
        return s + "]"


def _env_static_index() -> Optional[StaticIndex]:
    path = os.environ.get("HVD_TPU_SANITIZER_STATIC_INDEX", "").strip()
    return StaticIndex.load(path) if path else None


class CollectiveSanitizer:
    """Per-engine ledger recorder + digest tagger."""

    def __init__(self, capacity: int = 512, content_hash: bool = False,
                 static_index: Optional[StaticIndex] = None):
        self.capacity = capacity
        # Static call-graph linkage for reports (StaticIndex docstring).
        self.static_index = static_index if static_index is not None \
            else _env_static_index()
        # HVD_TPU_SANITIZER=hash: fold a content digest of each entry's
        # LOCAL contribution into the tag.  Costs one device→host copy per
        # submission — the documented price of closing the same-site
        # blind spot.
        self.content_hash = content_hash
        self._lock = threading.Lock()
        # Sequence counters are PER PROCESS SET: subgroup collectives are
        # legitimately submitted only by member ranks, so a single global
        # counter would drift on non-members and every later world
        # collective would false-positive.  Within one set, every member
        # submits the same sequence — which is exactly what the tag checks.
        self._seq: dict = collections.defaultdict(int)
        self.ledger: Deque[LedgerEntry] = collections.deque(maxlen=capacity)
        # Per-process-set views of the same stream: a tenant's divergence
        # report can quote ITS submissions only, without another set's
        # interleaved traffic pushing the relevant entries out of the
        # tail.  Each view is bounded like the combined ledger.
        self.ledgers: Dict[int, Deque[LedgerEntry]] = \
            collections.defaultdict(
                lambda: collections.deque(maxlen=capacity))

    # ------------------------------------------------------------- recording
    def observe(self, entries: Sequence, site: Optional[str] = None,
                hash_content: bool = True) -> None:
        """Record and tag freshly built engine entries (pre-negotiation).

        ``hash_content=False`` skips the content digest even in hash mode
        (synthesized join entries: never announced, and their identity
        fill would pointlessly pay the host copy)."""
        site = site or _caller_site()
        hashes = {}
        if self.content_hash and hash_content:
            # Outside the lock: device→host copies can be slow and must
            # not serialize concurrent submitters more than they already
            # do.  Entries are not yet shared with the engine queue here.
            for e in entries:
                hashes[id(e)] = self._content_hash(e)
        with self._lock:
            for e in entries:
                ps = getattr(e, "process_set_id", 0)
                seq = self._seq[ps]
                self._seq[ps] = seq + 1
                digest = self._entry_digest(e)
                tag = f"seq={ps}:{seq};site={site}"
                h = hashes.get(id(e))
                if h is not None:
                    tag += f";h={h}"
                # Stamped onto the entry: the controller ships it beside
                # the digest (full announce tag field / bitvector side-
                # channel) and the server folds it into its mismatch
                # comparison — order/call-site divergence becomes an
                # attributable per-tensor error on either wire path.
                e.sanitizer_tag = tag
                rec = LedgerEntry(seq=seq, name=e.name, digest=digest,
                                  site=site, process_set=ps)
                self.ledger.append(rec)
                self.ledgers[ps].append(rec)

    def rollback(self, entries: Sequence) -> None:
        """Undo :meth:`observe` for entries whose queue push was rejected
        (rank-local duplicate-name error): peers never see them, so their
        seq advances must not stand.  Entries are unwound newest-first;
        if another thread observed in between (non-contiguous counter),
        the unwind stops and a warning notes the possible skew."""
        with self._lock:
            for e in reversed(list(entries)):
                tag = getattr(e, "sanitizer_tag", "")
                try:
                    ps_s, seq_s = tag.split(";", 1)[0][len("seq="):].split(":")
                    ps, seq = int(ps_s), int(seq_s)
                except (ValueError, IndexError):  # pragma: no cover
                    continue
                if self._seq[ps] == seq + 1:
                    self._seq[ps] = seq
                    if self.ledger and self.ledger[-1].seq == seq \
                            and self.ledger[-1].name == e.name:
                        self.ledger.pop()
                    view = self.ledgers.get(ps)
                    if view and view[-1].seq == seq \
                            and view[-1].name == e.name:
                        view.pop()
                else:
                    log.warning(
                        "sanitizer: cannot roll back seq %d:%d for %r "
                        "(concurrent submissions interleaved); cross-rank "
                        "seq tags may skew from here", ps, seq, e.name)
                    break

    def observe_synthesized(self, entry) -> None:
        """Account for an entry synthesized while this rank is JOINED
        (engine._synthesize_join_entry): the peer advanced its counter by
        submitting, so this rank must too, or every post-join collective
        would mismatch on seq.  Synthesized entries are never announced, so
        the tag itself doesn't hit the wire — only the counter matters."""
        self.observe([entry], site="<joined:synthesized>", hash_content=False)

    @staticmethod
    def _content_hash(e) -> Optional[str]:
        """Digest of this rank's LOCAL contribution (the addressable
        shards of a multi-process global array; the whole array in
        single-controller mode).  Returns None when the entry carries no
        tensor (barrier) or the copy fails — the tag then simply omits
        the hash field, and the server compares what both sides sent."""
        t = getattr(e, "tensor", None)
        if t is None:
            return None
        import hashlib
        import numpy as np
        h = hashlib.blake2b(digest_size=8)
        try:
            shards = getattr(t, "addressable_shards", None)
            if shards:
                for s in shards:
                    h.update(np.ascontiguousarray(
                        np.asarray(s.data)).tobytes())
            else:
                h.update(np.ascontiguousarray(np.asarray(t)).tobytes())
        except Exception:  # noqa: BLE001 - diagnostics must not kill submit
            return None
        return h.hexdigest()

    @staticmethod
    def _entry_digest(e) -> str:
        t = getattr(e, "tensor", None)
        ct = getattr(e, "ctype", None)
        parts = [getattr(ct, "value", "op")]
        if t is not None:
            shape = tuple(t.shape[1:]) if len(t.shape) else ()
            parts += [str(t.dtype), str(shape)]
        op = getattr(e, "reduce_op", None)
        if op is not None:
            parts.append(op.name)
        return "|".join(parts)

    # ------------------------------------------------------------- reporting
    def tail(self, n: int = 8,
             process_set: Optional[int] = None) -> List[LedgerEntry]:
        """Last ``n`` ledger entries — combined stream by default, one
        process set's view when ``process_set`` is given."""
        with self._lock:
            src = self.ledger if process_set is None \
                else self.ledgers.get(process_set, ())
            return list(src)[-n:]

    def render_tail(self, n: int = 8,
                    process_set: Optional[int] = None) -> str:
        entries = self.tail(n, process_set=process_set)
        scope = "" if process_set is None \
            else f" (process set {process_set})"
        if not entries:
            return f"(collective ledger{scope} empty)"
        idx = self.static_index

        def line(e: LedgerEntry) -> str:
            return e.render() + (idx.annotate(e.site) if idx else "")

        return f"last submissions on this rank{scope}:\n  " + \
            "\n  ".join(line(e) for e in entries)


class SanitizerStallInspector:
    """Drop-in wrapper for the engine's StallInspector: tightened timeout,
    ledger-tail attribution on every stall report (HVD302), laggard rank
    names passed through from negotiation."""

    def __init__(self, inner, sanitizer: CollectiveSanitizer,
                 warn_after_s: float):
        self._inner = inner
        self._sanitizer = sanitizer
        # Installed by the monitor subsystem (horovod_tpu.monitor
        # MonitorAgent): a zero-arg callable returning the PEER ranks'
        # ledger tails from the cross-rank aggregation table, so a stall
        # report shows what the laggard last submitted — not only this
        # rank's own tail (the ROADMAP ledger-exchange item).
        self.peer_ledger_source = None
        # The sanitizer timeout is authoritative in BOTH directions: the
        # README documents HVD_TPU_SANITIZER_TIMEOUT as the stall-report
        # threshold, so raising it past HOROVOD_STALL_CHECK_TIME must work
        # (slow first steps), not silently clamp to the smaller value.
        self._inner.warn_after_s = warn_after_s
        # An explicit HOROVOD_STALL_CHECK_DISABLE wins: the sanitizer then
        # provides ledger/digest checks only, no stall policing.
        if inner.disabled:
            log.info("sanitizer: stall reporting stays OFF "
                     "(HOROVOD_STALL_CHECK_DISABLE is set)")
        # Mirrored so the engine's config reads keep working.
        self.warn_after_s = self._inner.warn_after_s
        self.shutdown_after_s = inner.shutdown_after_s
        self.disabled = inner.disabled

    def progressed(self, name: str):
        """Completion epilogue passthrough (the engine calls this on every
        settle): clears the inner inspector's warned latch so a later
        collective reusing the name warns afresh."""
        self._inner.progressed(name)

    @property
    def stalled(self):
        """Live stall state passthrough (monitor /health export)."""
        return self._inner.stalled

    def _peer_report(self) -> str:
        if self.peer_ledger_source is None:
            return ""
        try:
            report = self.peer_ledger_source()
        except Exception:  # noqa: BLE001 - diagnostics only
            return ""
        return f"\n{report}" if report else ""

    def check(self, waiting, missing_ranks=None):
        before = set(self._inner._warned)
        try:
            self._inner.check(waiting, missing_ranks)
        except RuntimeError as exc:
            raise RuntimeError(
                f"{exc}\nHVD302 sanitizer: {self._sanitizer.render_tail()}"
                f"{self._peer_report()}"
            ) from None
        newly = set(self._inner._warned) - before
        if newly:
            tags = {e.name: getattr(e, "sanitizer_tag", "") for e in waiting}
            for name in sorted(newly):
                site = tags.get(name, "")
                site = site.split("site=", 1)[1] if "site=" in site else "?"
                site = site.split(";", 1)[0]
                if self._sanitizer.static_index is not None:
                    site += self._sanitizer.static_index.annotate(site)
                log.warning(
                    "HVD302 sanitizer: collective %r (submitted at %s) is "
                    "stalled%s; %s%s", name, site,
                    (f" waiting on ranks {missing_ranks[name]}"
                     if missing_ranks and name in missing_ranks else ""),
                    self._sanitizer.render_tail(), self._peer_report())


def maybe_install(engine) -> Optional[CollectiveSanitizer]:
    """Attach a sanitizer to a freshly built CollectiveEngine when the env
    opts in; returns it (or None).  Called from the engine constructor so
    every init()'d runtime — JAX, torch or TF binding — is covered."""
    m = mode()
    if m is None:
        return None
    capacity = int(os.environ.get("HVD_TPU_SANITIZER_LEDGER", "512") or 512)
    timeout = float(os.environ.get("HVD_TPU_SANITIZER_TIMEOUT", "30") or 30)
    sanitizer = CollectiveSanitizer(capacity=capacity,
                                    content_hash=(m == "hash"))
    engine.stall = SanitizerStallInspector(engine.stall, sanitizer, timeout)
    log.info("collective sanitizer enabled (mode=%s, timeout=%.1fs, "
             "ledger=%d)", m, timeout, capacity)
    return sanitizer
