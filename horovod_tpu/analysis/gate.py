"""The whole-package self-lint CI gate.

Runs the two-pass interprocedural analyzer over this repo's own
``horovod_tpu/`` + ``examples/`` + ``tools/`` trees, subtracts the reviewed
baseline (``tools/lint_baseline.json``), and exits nonzero on any NEW
finding — error or warning severity alike, because a silent warning creep
is exactly what a baseline is for.  Stale baseline entries (code fixed,
lines moved) are reported so the file shrinks over time; the tier-1 suite
(``tests/test_lint_self.py``) asserts both "no new findings" and "no stale
entries".

Invocations:
  python tools/lint_gate.py                 # the gate (CI / tier-1)
  python tools/lint_gate.py --update-baseline   # re-baseline after review
  python tools/lint_gate.py --explain HVD113:horovod_tpu/x.py:42
                                            # why did this finding fire?
  hvd-lint-gate                             # console script (pyproject)

``--explain RULE:path:line`` re-runs the analyzer and prints the full
story behind one finding — the interprocedural call chain and the
resolved process-set values — so deciding whether to baseline it stops
requiring a debugger.

Exit status: 0 gate passes, 1 new findings, 3 analyzer crash (matching
``python -m horovod_tpu.analysis`` CI contract).
"""

from __future__ import annotations

import argparse
import os
import sys
import traceback

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))
SCOPE = ("horovod_tpu", "examples", "tools", "bench.py")
BASELINE = os.path.join("tools", "lint_baseline.json")


def run_gate(root: str = REPO_ROOT, update_baseline: bool = False,
             sarif: str | None = None, quiet: bool = False):
    """Returns (new_findings, stale_keys, baselined_count)."""
    from .baseline import diff_baseline, load_baseline, write_baseline
    from .whole_package import analyze_package

    paths = [os.path.join(root, p) for p in SCOPE
             if os.path.exists(os.path.join(root, p))]
    baseline_path = os.path.join(root, BASELINE)
    findings = analyze_package(paths)

    if update_baseline:
        write_baseline(findings, baseline_path, root=root)
        if not quiet:
            print(f"wrote {len(findings)} finding(s) to {baseline_path}")
        return [], [], len(findings)

    diff = diff_baseline(findings, load_baseline(baseline_path), root=root)
    if sarif:
        from .sarif import write_sarif
        write_sarif(diff.new, sarif, root=root)
    return diff.new, diff.stale, len(diff.matched)


def explain(spec: str, root: str = REPO_ROOT, quiet: bool = False) -> int:
    """``--explain RULE:path:line``: print the interprocedural chain and
    resolved process-set values behind one finding.  Returns 0 when the
    finding exists, 1 when nothing at that key fires."""
    from .whole_package import analyze_package
    from .baseline import _rel

    try:
        rule, rest = spec.split(":", 1)
        path, line_s = rest.rsplit(":", 1)
        line = int(line_s)
    except ValueError:
        print(f"error: --explain wants RULE:path:line, got {spec!r}",
              file=sys.stderr)
        return 2

    paths = [os.path.join(root, p) for p in SCOPE
             if os.path.exists(os.path.join(root, p))]
    findings = analyze_package(paths)
    # Match the finding's repo-relative path by suffix, so both
    # "horovod_tpu/x.py" and a bare "x.py" select the site.
    rel_want = path.replace(os.sep, "/").lstrip("./")
    hits = [f for f in findings
            if f.rule == rule and f.line == line
            and _rel(f.path, root).lstrip("/").endswith(rel_want)]
    if not hits:
        if not quiet:
            print(f"no {rule} finding at {path}:{line} "
                  f"(the analyzer reports {len(findings)} finding(s) "
                  f"package-wide)")
        return 1
    for f in hits:
        print(f.render())
        if f.process_set:
            print(f"  process set(s): {f.process_set}")
        if f.chain:
            print("  call chain:")
            for hop in f.chain:
                print(f"    {hop}")
        if f.related:
            print("  related collective sites:")
            for rp, rl in f.related:
                print(f"    {_rel(rp, root).lstrip('/')}:{rl}")
    return 0


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="lint_gate",
        description="Whole-package collective-correctness self-lint gate "
                    "(horovod_tpu/ + examples/ + tools/ vs the reviewed "
                    "baseline).")
    ap.add_argument("--root", default=REPO_ROOT,
                    help="repo root (default: autodetected)")
    ap.add_argument("--update-baseline", action="store_true",
                    help="rewrite tools/lint_baseline.json from the "
                         "current findings (after human review)")
    ap.add_argument("--sarif", metavar="FILE",
                    help="also write NEW findings as SARIF 2.1.0")
    ap.add_argument("--explain", metavar="RULE:path:line",
                    help="print the interprocedural chain + resolved "
                         "process-set values behind one finding")
    args = ap.parse_args(argv)

    # Guard the console-script case: installed into site-packages, the
    # autodetected root is site-packages and the gate would "find" zero
    # baseline + scan the wrong tree.  Demand a real source checkout.
    if not os.path.isfile(os.path.join(args.root, "pyproject.toml")):
        print(f"error: {args.root!r} does not look like the horovod_tpu "
              f"repo (no pyproject.toml) — pass --root <checkout>",
              file=sys.stderr)
        return 2

    if args.explain:
        try:
            return explain(args.explain, root=args.root)
        except Exception:  # noqa: BLE001 - crash != finding (CI contract)
            print("internal error: --explain crashed (exit 3)",
                  file=sys.stderr)
            traceback.print_exc()
            return 3

    try:
        new, stale, baselined = run_gate(
            root=args.root, update_baseline=args.update_baseline,
            sarif=args.sarif)
    except Exception:  # noqa: BLE001 - crash != finding (CI contract)
        print("internal error: lint gate crashed (exit 3)", file=sys.stderr)
        traceback.print_exc()
        return 3

    if args.update_baseline:
        return 0
    for f in new:
        print(f.render())
    if stale:
        print(f"note: {len(stale)} stale baseline entr"
              + ("y" if len(stale) == 1 else "ies")
              + " no longer fire(s) — prune tools/lint_baseline.json:")
        for r, p, ln in stale:
            print(f"  {r} {p}:{ln}")
    print(f"lint gate: {len(new)} new finding(s), {baselined} baselined, "
          f"{len(stale)} stale")
    return 1 if new else 0


if __name__ == "__main__":
    sys.exit(main())
