"""Collective-correctness analyzer: lint + trace check + runtime sanitizer.

Three layers, one rule catalog (see ``findings.RULES`` and
``docs/analysis.md``):

- :mod:`.collective_lint` — AST lint of training scripts (and this repo),
  no jax required.  CLI: ``python -m horovod_tpu.analysis <paths>``.
- :mod:`.trace_check` — jaxpr-level collective ledger audit of a traced
  step function.
- :mod:`.runtime_sanitizer` — ``HVD_TPU_SANITIZER=1`` run-time ledger and
  cross-rank order/signature check through the negotiation controller.

Framework bindings expose this as ``DistributedOptimizer(..., check=...)``
(see :mod:`.hooks`).
"""

from .findings import Finding, Rule, RULES, Severity, summarize  # noqa: F401
from .collective_lint import (  # noqa: F401
    COLLECTIVE_NAMES, lint_file, lint_paths, lint_source,
)

__all__ = [
    "Finding", "Rule", "RULES", "Severity", "summarize",
    "COLLECTIVE_NAMES", "lint_file", "lint_paths", "lint_source",
    "analyze_paths",
]


def analyze_paths(paths, include_warnings: bool = True):
    """Lint files/dirs; returns findings (errors first, then warnings)."""
    findings = lint_paths(paths)
    if not include_warnings:
        findings = [f for f in findings if f.is_error]
    return sorted(findings, key=lambda f: (not f.is_error, f.path, f.line))
