"""Collective-correctness analyzer: lint + trace check + runtime sanitizer.

Three layers, one rule catalog (see ``findings.RULES`` and
``docs/analysis.md``):

- :mod:`.collective_lint` — AST lint of training scripts (and this repo),
  no jax required.  CLI: ``python -m horovod_tpu.analysis <paths>``.
- :mod:`.trace_check` — jaxpr-level collective ledger audit of a traced
  step function.
- :mod:`.runtime_sanitizer` — ``HVD_TPU_SANITIZER=1`` run-time ledger and
  cross-rank order/signature check through the negotiation controller.

Plus the two-pass **whole-package mode** (``--whole-package``; see
:mod:`.callgraph` / :mod:`.whole_package`): a package-wide symbol table +
call graph, interprocedural HVD101 rank-guard propagation, cross-module
HVD102/HVD103 facts, per-entry-point collective schedules (HVD108/HVD109),
SARIF 2.1.0 output (:mod:`.sarif`), finding baselines (:mod:`.baseline`)
and the repo's CI gate (:mod:`.gate`, ``tools/lint_gate.py``).

Framework bindings expose this as ``DistributedOptimizer(..., check=...)``
(see :mod:`.hooks`).
"""

from .findings import Finding, Rule, RULES, Severity, summarize  # noqa: F401
from .collective_lint import (  # noqa: F401
    COLLECTIVE_NAMES, lint_file, lint_paths, lint_source,
)

__all__ = [
    "Finding", "Rule", "RULES", "Severity", "summarize",
    "COLLECTIVE_NAMES", "lint_file", "lint_paths", "lint_source",
    "analyze_paths", "analyze_package", "build_package",
]


def analyze_package(paths):
    """Whole-package (interprocedural) analysis; see
    :func:`.whole_package.analyze_package`.  Imported lazily so the plain
    per-module lint path stays import-light."""
    from .whole_package import analyze_package as _ap
    return _ap(paths)


def build_package(paths):
    """Build the pass-1 symbol table + call graph; see
    :func:`.callgraph.build_package`."""
    from .callgraph import build_package as _bp
    return _bp(paths)


def analyze_paths(paths, include_warnings: bool = True):
    """Lint files/dirs; returns findings (errors first, then warnings)."""
    findings = lint_paths(paths)
    if not include_warnings:
        findings = [f for f in findings if f.is_error]
    return sorted(findings, key=lambda f: (not f.is_error, f.path, f.line))
