"""Finding baselines: known findings that must not block a CI gate.

A baseline is a JSON file listing reviewed findings keyed by
``(rule, repo-relative path, line)``.  The gate (``tools/lint_gate.py``)
subtracts the baseline from a fresh run: only NEW findings fail CI, and
entries that no longer fire are reported as stale so the file shrinks as
code is fixed — the same honesty contract as ``tests/test_lint_self.py``'s
inline allowlist, but file-based so the whole-package mode's reviewed
findings (benchmarks, deliberate test divergence) don't need source edits
in bulk.
"""

from __future__ import annotations

import dataclasses
import json
import os
from typing import Dict, Iterable, List, Optional, Sequence, Tuple

from .findings import Finding

Key = Tuple[str, str, int]


def _rel(path: str, root: Optional[str]) -> str:
    path = os.path.abspath(path)
    if root:
        root = os.path.abspath(root)
        if path == root or path.startswith(root + os.sep):
            path = os.path.relpath(path, root)
    return path.replace(os.sep, "/")


def finding_key(f: Finding, root: Optional[str] = None) -> Key:
    return (f.rule, _rel(f.path, root), f.line)


@dataclasses.dataclass
class BaselineDiff:
    new: List[Finding]
    matched: List[Finding]
    stale: List[Key]


def load_baseline(path: str) -> Dict[Key, str]:
    """Baseline file → {key: reason/message}.  Missing file → empty."""
    if not os.path.isfile(path):
        return {}
    with open(path, "r", encoding="utf-8") as fh:
        data = json.load(fh)
    out: Dict[Key, str] = {}
    for e in data.get("findings", []):
        out[(e["rule"], e["path"], int(e["line"]))] = e.get("message", "")
    return out


def write_baseline(findings: Iterable[Finding], path: str,
                   root: Optional[str] = None) -> None:
    entries = [{
        "rule": f.rule,
        "path": _rel(f.path, root),
        "line": f.line,
        "message": f.message,
    } for f in findings]
    entries.sort(key=lambda e: (e["path"], e["line"], e["rule"]))
    with open(path, "w", encoding="utf-8") as fh:
        json.dump({"version": 1, "findings": entries}, fh, indent=2)
        fh.write("\n")


def diff_baseline(findings: Sequence[Finding], baseline: Dict[Key, str],
                  root: Optional[str] = None) -> BaselineDiff:
    """Split findings into new vs baseline-matched; report stale entries."""
    live: Dict[Key, None] = {}
    new: List[Finding] = []
    matched: List[Finding] = []
    for f in findings:
        k = finding_key(f, root)
        live[k] = None
        (matched if k in baseline else new).append(f)
    stale = [k for k in baseline if k not in live]
    return BaselineDiff(new=new, matched=matched, stale=sorted(stale))
