"""Finding model + rule catalog for the collective-correctness analyzer.

The reference devotes C++ runtime machinery (message-table negotiation in
``controller.cc``, the stall inspector — SURVEY.md §L2) to diagnosing ranks
that disagree about collectives.  In the TPU rebuild most of those bugs are
visible in the Python source or the traced jaxpr, so each known failure mode
gets a *rule* here and the three analyzer layers (``collective_lint``,
``trace_check``, ``runtime_sanitizer``) emit :class:`Finding` records
against this shared catalog.

This module and the linter are deliberately jax-free: the lint path only
parses source text, so ``python -m horovod_tpu.analysis`` never executes
user code, initializes the runtime, or touches a device.
"""

from __future__ import annotations

import dataclasses
import enum
import os
from typing import Dict, List, Optional

_PKG_DIR = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def is_package_frame(filename: str) -> bool:
    """True when a stack frame's file belongs to the horovod_tpu package.

    Shared by the ``check=`` hook's caller discovery and the runtime
    sanitizer's call-site attribution.  Matched by path prefix, NOT
    substring — a user's ``~/horovod_tpu/train.py`` is user code.
    """
    return filename == _PKG_DIR or filename.startswith(_PKG_DIR + os.sep)


class Severity(enum.Enum):
    ERROR = "error"      # will deadlock / corrupt numerics on some worlds
    WARNING = "warning"  # divergence-prone; needs human judgement

    def __str__(self) -> str:  # pragma: no cover - repr sugar
        return self.value


@dataclasses.dataclass(frozen=True)
class Rule:
    id: str
    severity: Severity
    title: str
    rationale: str
    fix_hint: str


# The catalog.  IDs are stable API: suppression comments, allowlists and the
# docs reference them.  1xx = source lint, 2xx = jaxpr trace, 3xx = runtime.
RULES: Dict[str, Rule] = {r.id: r for r in [
    Rule(
        "HVD101", Severity.ERROR,
        "collective under rank-divergent control flow",
        "A collective inside an `if rank() == 0:`-style branch (or after an "
        "early return taken only by some ranks) is submitted by a subset of "
        "ranks; the peers block in negotiation forever and the job wedges "
        "with no diagnostics — the reference's #1 stall-inspector report.",
        "Hoist the collective out of the branch so every rank submits it, "
        "or restrict it to a registered process_set whose members all take "
        "the branch.",
    ),
    Rule(
        "HVD102", Severity.WARNING,
        "collective missing process_set while subgroup sets exist",
        "Once add_process_set() carves subgroups, a collective that omits "
        "process_set= targets the GLOBAL set.  If only the subgroup's ranks "
        "reach the call site, the rest of the world never submits and the "
        "job deadlocks at the readiness threshold.",
        "Pass process_set= explicitly on every collective issued from code "
        "paths only subgroup members execute.",
    ),
    Rule(
        "HVD103", Severity.WARNING,
        "missing broadcast_parameters after init()",
        "Training starts from per-rank random init: without a rank-0 "
        "broadcast of params/optimizer state after init(), ranks average "
        "gradients of DIFFERENT models and silently diverge (reference: "
        "Usage step 4, broadcast_parameters/broadcast_optimizer_state).",
        "Call broadcast_parameters(...) (and broadcast_optimizer_state) "
        "right after init(), or manage state through hvd.elastic state "
        "sync.",
    ),
    Rule(
        "HVD104", Severity.ERROR,
        "collective ordered by set iteration",
        "Python set iteration order is hash-randomized across processes "
        "(PYTHONHASHSEED): each rank submits the collectives in a different "
        "sequence, scrambling fusion-bucket order and pairing different "
        "tensors under one negotiated name — deadlock or silent corruption.",
        "Iterate over sorted(the_set) so every rank submits in one order.",
    ),
    Rule(
        "HVD105", Severity.WARNING,
        "collective ordered by dict iteration",
        "Dict iteration follows insertion order, which drifts across ranks "
        "whenever the dicts were built differently (conditionally inserted "
        "keys, checkpoint-restored vs fresh).  Divergent submission order "
        "scrambles fusion buckets across ranks.",
        "Iterate over sorted(d.items()) — the reference does exactly this "
        "for named_parameters.",
    ),
    Rule(
        "HVD106", Severity.ERROR,
        "host sync/callback inside jit",
        "block_until_ready / io_callback / pure_callback inside a jitted "
        "function forces a host round-trip per step (or traces to a stub): "
        "on multi-process TPU the host sync point can interleave "
        "differently per rank and wedge the collective schedule.",
        "Move host syncs outside the jitted step; use jax.debug.print for "
        "in-graph debugging.",
    ),
    Rule(
        "HVD107", Severity.ERROR,
        "eager engine collective traced under jit",
        "hvd.allreduce()-family eager ops submit to the background engine "
        "at TRACE time, not run time: under jit the collective runs once "
        "during compilation and never again, so ranks diverge after the "
        "first step (and re-traces deadlock peers).",
        "Use the in-graph form (lax.psum / C.allreduce with axis_name "
        "inside shard_map), or call the eager op outside jit.",
    ),
    Rule(
        "HVD108", Severity.WARNING,
        "branch-divergent collective schedule",
        "Two paths through one function emit different collective sequences "
        "(whole-package analysis, call chains included).  Unless the branch "
        "condition is provably identical on every rank, ranks taking "
        "different paths submit different schedules — negotiation wedges at "
        "the readiness threshold or pairs the wrong tensors under one slot. "
        "Horovod-style background negotiation assumes every rank submits "
        "THE SAME schedule; this rule proves it per branch statically.",
        "Make both branches emit the same collective sequence (hoist the "
        "collectives out of the branch), or ensure the condition is "
        "rank-invariant (derived from size()/hyperparameters, not data).",
    ),
    Rule(
        "HVD109", Severity.ERROR,
        "collective reachable from an elastic/churn transition callback",
        "A collective is reachable (through the call graph) from an "
        "elastic-transition handler (on_leave / new_generation / "
        "on_hosts_updated / preemption hooks).  Those callbacks run while "
        "the rank set is MID-TRANSITION: peers may already have left or not "
        "yet joined, so the collective negotiates against a world that is "
        "being torn down — the fleet wedges with no diagnostics.",
        "Defer the collective until after re-rendezvous completes (elastic "
        "state sync on restore), or restrict it to a process_set formed "
        "from the post-transition world.",
    ),
    Rule(
        "HVD110", Severity.ERROR,
        "world-divergent collective data-plane configuration",
        "A sharded= / shard-count / hierarchical= argument of a "
        "collective or a DistributedOptimizer/sharded_optimizer wrapper "
        "is derived from rank identity.  The sharded flag is part of the "
        "negotiation digest and shapes the whole data plane "
        "(reduce-scatter + allgather vs allreduce; 1/N shard layouts); "
        "the hierarchical override rides the fusion key only, but "
        "batching groups entries by fusion key, so divergence still "
        "forks the batch plan: ranks disagreeing submit mismatched "
        "programs — negotiation fails fast at best, or the fleet wedges "
        "mid-collective at worst.",
        "Make the data-plane configuration a fleet-uniform constant "
        "(hyperparameter, HOROVOD_SHARDED_OPTIMIZER / --sharded, "
        "HOROVOD_HIERARCHICAL_ALLREDUCE / HOROVOD_HIER_THRESHOLD), never "
        "a function of rank()/local_rank().",
    ),
    Rule(
        "HVD111", Severity.ERROR,
        "branch-divergent interleaving of overlapping process sets",
        "Two paths through one function submit collectives over two "
        "process sets that share ranks in DIFFERENT interleavings.  Each "
        "set is its own communicator with its own ordered stream, but the "
        "shared ranks execute submissions in program order: rank A holds "
        "set-1's slot while waiting on set-2, rank B holds set-2's slot "
        "while waiting on set-1 — the classic cross-communicator deadlock "
        "(MPI forbids exactly this; Horovod's per-communicator negotiation "
        "cannot detect it because each lane looks self-consistent).",
        "Give the overlapping sets one fixed relative submission order on "
        "every path (hoist the collectives out of the branch), or make "
        "the sets disjoint so their streams cannot entangle.",
    ),
    Rule(
        "HVD112", Severity.ERROR,
        "collective axis absent from its binding mesh/PartitionSpec",
        "A shard_map/in-graph collective names an axis_name (or a "
        "PartitionSpec names an axis) that the binding mesh does not "
        "define — the fsdp-by-tp mismatch.  At best lowering fails; at "
        "worst a differently-built mesh binds the name to a 1-sized axis "
        "and the reduction silently becomes a no-op on every rank.",
        "Use an axis name the binding mesh actually defines (check "
        "make_mesh()/process_set_mesh(axis_name=...) at the shard_map "
        "site), and keep PartitionSpecs within the mesh's axis set.",
    ),
    Rule(
        "HVD113", Severity.ERROR,
        "hard-coded world collective reachable from a process-set-scoped region",
        "Code scoped to a registered process set (helpers called with "
        "process_set=<set>, or functions that take a process_set and use "
        "it) reaches a collective that omits process_set= and therefore "
        "targets the GLOBAL set.  In a multi-tenant world only the set's "
        "members run this region: the world collective waits on ranks "
        "that never arrive (tenant-leak deadlock), and if they DO arrive "
        "it silently mixes tenants' data.",
        "Thread the process_set through to every collective in the scoped "
        "region (forward the parameter), or hoist the deliberate world "
        "sync out of the set-scoped code path.",
    ),
    Rule(
        "HVD114", Severity.WARNING,
        "overlapping process sets interleaved without a dominating order edge",
        "A function alternates submissions between two process sets that "
        "share ranks (set-1, set-2, set-1 ...) with no world-level "
        "barrier establishing a dominating order edge between the lanes.  "
        "Each lane is self-consistent, but nothing orders them against "
        "each other: any rank-dependent scheduling skew (HVD111's dynamic "
        "cousin) can entangle the shared ranks' streams.",
        "Insert hvd.barrier() between the lanes, batch each set's "
        "collectives contiguously, or make the sets disjoint.",
    ),
    Rule(
        "HVD201", Severity.ERROR,
        "collective over unknown mesh axis",
        "A traced lax collective names an axis_name the surrounding mesh "
        "does not bind; under pjit/shard_map this fails at lowering — or "
        "worse, silently reduces over a 1-sized axis on a differently-"
        "built mesh.",
        "Make the collective's axis_name match an axis of the mesh the "
        "step is shard_map'ped over.",
    ),
    Rule(
        "HVD202", Severity.ERROR,
        "axis_index_groups do not partition the axis",
        "psum/all_gather with axis_index_groups that skip or repeat a rank "
        "make the skipped ranks wait on a collective they never joined.",
        "Every rank 0..axis_size-1 must appear in exactly one group.",
    ),
    Rule(
        "HVD203", Severity.WARNING,
        "host callback primitive in traced step",
        "The traced step contains a host callback (io_callback / "
        "pure_callback / debug_callback): per-step host round-trips "
        "serialize the device pipeline and order differently per rank.",
        "Keep callbacks out of the hot step; aggregate on device and "
        "fetch outside.",
    ),
    Rule(
        "HVD204", Severity.ERROR,
        "ppermute permutation is not a bijection over the axis",
        "lax.ppermute with a perm that repeats a source/destination, names "
        "a rank outside the axis, or leaves ranks uncovered makes the "
        "uncovered/over-covered ranks exchange with partners that never "
        "send — the same deadlock shape as bad axis_index_groups (HVD202). "
        "JAX's single-host semantics mask it (missing pairs read zeros); "
        "a multi-host launch wedges.",
        "Make perm a bijection: every rank 0..axis_size-1 appears exactly "
        "once as a source and exactly once as a destination (e.g. a full "
        "ring [(i, (i + 1) % n) for i in range(n)]).",
    ),
    Rule(
        "HVD301", Severity.ERROR,
        "cross-rank collective order/signature divergence",
        "At runtime, ranks submitted different collectives (or the same "
        "ones in different order / from different call sites) under one "
        "negotiated sequence slot.",
        "Inspect the two call sites named in the message; make every rank "
        "issue the same collective sequence.",
    ),
    Rule(
        "HVD302", Severity.WARNING,
        "collective stalled waiting on laggard ranks",
        "A submitted collective has waited past the sanitizer timeout; the "
        "named ranks have not submitted their contribution.",
        "Check the laggard ranks' logs for the branch they took instead; "
        "the ledger tail in this report shows the last calls they made.",
    ),
    Rule(
        "HVD303", Severity.ERROR,
        "control-plane peer failure (dead or unresponsive rank)",
        "The coordinator declared one or more ranks dead — their socket "
        "died (process crash, ECONNRESET) or they missed the per-round "
        "deadline (HOROVOD_ROUND_TIMEOUT_S) — and broadcast a typed ABORT "
        "to the survivors, which surface it as PeerFailureError (or "
        "RoundTimeoutError when this rank's own round deadline expired "
        "without a verdict).  Without this machinery every surviving rank "
        "would block in a deadline-free recv until a human killed the "
        "job.",
        "Check the named ranks' logs for the crash; under the elastic "
        "driver the survivors re-rendezvous automatically — otherwise "
        "restart the job without the dead host.  docs/fault_tolerance.md "
        "covers the knobs.",
    ),
]}


@dataclasses.dataclass
class Finding:
    """One analyzer result, printable as ``path:line:col: ID severity msg``."""
    rule: str
    path: str
    line: int
    col: int
    message: str
    severity: Optional[Severity] = None
    fix_hint: Optional[str] = None
    # Interprocedural provenance (filled by the whole-package passes, used by
    # `lint_gate --explain` and the SARIF `processSet` property).  Appended
    # after the original fields so positional construction stays valid.
    chain: Optional[List[str]] = None          # call path, caller -> site
    process_set: Optional[str] = None          # resolved process-set value(s)
    related: Optional[List[tuple]] = None      # [(path, line)] of involved sites

    def __post_init__(self):
        r = RULES.get(self.rule)
        if self.severity is None:
            self.severity = r.severity if r else Severity.WARNING
        if self.fix_hint is None and r is not None:
            self.fix_hint = r.fix_hint

    @property
    def is_error(self) -> bool:
        return self.severity == Severity.ERROR

    def render(self, show_fix: bool = True) -> str:
        s = f"{self.path}:{self.line}:{self.col}: {self.rule} " \
            f"{self.severity.value}: {self.message}"
        if show_fix and self.fix_hint:
            s += f"\n    fix: {self.fix_hint}"
        return s


def summarize(findings: List[Finding]) -> str:
    errs = sum(1 for f in findings if f.is_error)
    warns = len(findings) - errs
    return f"{len(findings)} finding(s): {errs} error(s), {warns} warning(s)"
