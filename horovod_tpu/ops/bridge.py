"""Shared numpy-level submit conventions for framework bindings.

The torch and TF bindings both bridge framework tensors through host numpy
into the eager layer; the SPMD conventions they must agree on live here so
they cannot drift (reference analogue: the common ``TensorTableEntry``
adapter layer under ``horovod/common/`` that N26/N27 both used):

- multi-process mode: one process = one rank's contribution, submitted
  as-is;
- single-controller SPMD: the process submits on behalf of every rank it
  owns — the same tensor replicated via a stride-0 view (no host copy);
- stacked sharded results → this rank's row(s);
- ragged alltoall: validate splits length, then either the local per-rank
  call (multi-process) or the replicated single-controller form.
"""

from __future__ import annotations

from typing import Optional

import numpy as np

from ..common import basics
from ..common.process_sets import ProcessSet
from . import eager


def set_size(process_set: Optional[ProcessSet]) -> int:
    return process_set.size() if process_set is not None else basics.size()


def replicate_for_controller(a: np.ndarray,
                             process_set: Optional[ProcessSet] = None):
    """Single-controller SPMD submission: every rank this process owns
    contributes the same tensor — a stride-0 broadcast view, so no dense
    world-sized host materialization."""
    return np.broadcast_to(a, (set_size(process_set),) + a.shape)


def submit_numpy(a: np.ndarray, process_set: Optional[ProcessSet] = None):
    if eager.per_process_mode():
        return a
    return replicate_for_controller(a, process_set)


def take_my_row(a: np.ndarray) -> np.ndarray:
    """Stacked sharded results ([world, *S] rows = per-rank outputs, or
    this process's [1, *S] / [local, *S] slice in multi-process mode) →
    this rank's row(s)."""
    if eager.per_process_mode():
        return a[0] if a.shape[0] == 1 else a.reshape(-1, *a.shape[2:])
    return a[basics.rank()]


class RaggedAsyncHandle:
    """Binding-level async handle for ragged alltoall: wraps the eager
    continuation and resolves to THIS rank's local ``(output,
    received_splits)`` in either launch mode."""

    def __init__(self, inner, controller_mode: bool):
        self._inner = inner
        self._controller = controller_mode

    def poll(self) -> bool:
        return eager.poll(self._inner)

    def synchronize(self):
        out, rsp = eager.synchronize(self._inner)
        if self._controller:
            r = basics.rank()
            return out[r], rsp[r]
        return out, rsp


def _ragged_args(a: np.ndarray, splits,
                 process_set: Optional[ProcessSet]):
    world = set_size(process_set)
    sp = np.asarray(splits).astype(np.int64).reshape(-1)
    if sp.size != world:
        raise ValueError(f"splits must have {world} entries, got {sp.size}")
    if eager.per_process_mode():
        return a, sp, False
    return [a] * world, np.tile(sp, (world, 1)), True


def ragged_alltoall_async_numpy(a: np.ndarray, splits,
                                name: Optional[str] = None,
                                process_set: Optional[ProcessSet] = None
                                ) -> RaggedAsyncHandle:
    """Async form of :func:`ragged_alltoall_numpy` (reference: the fully
    async-capable ``hvd.alltoall``)."""
    tensor, sp, controller = _ragged_args(a, splits, process_set)
    inner = eager.alltoall_async(tensor, splits=sp, name=name,
                                 process_set=process_set)
    return RaggedAsyncHandle(inner, controller)


def ragged_alltoall_numpy(a: np.ndarray, splits,
                          name: Optional[str] = None,
                          process_set: Optional[ProcessSet] = None):
    """Ragged alltoall for one rank's numpy contribution; returns
    ``(output, received_splits)`` for THIS rank."""
    return ragged_alltoall_async_numpy(a, splits, name=name,
                                       process_set=process_set).synchronize()
