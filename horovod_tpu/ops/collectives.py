"""In-graph collective primitives over a mesh axis.

TPU-native replacement for the reference's L1 collective ops
(``horovod/common/ops/`` — NCCL/MPI/Gloo classes behind ``OperationManager``,
SURVEY.md §2a N14–N21).  On TPU there is exactly one data plane — XLA
collectives over ICI — so the strategy-dispatch layer collapses: these are
thin, composable wrappers over ``jax.lax`` collectives, usable inside
``shard_map`` / ``pjit``.  The dynamic/eager path (``ops/engine.py``) compiles
these same primitives into fused micro-programs.

All functions take an ``axis_name`` (default ``"hvd"``, the world axis) and
work over any mesh axis or axis tuple, which is what makes them the building
blocks for TP/SP/EP meshes as well (SURVEY.md §2c).
"""

from __future__ import annotations

import enum
from typing import Optional, Sequence, Union

import jax
import jax.numpy as jnp
from jax import lax
from ..compat import axis_size as compat_axis_size

AxisName = Union[str, Sequence[str]]
DEFAULT_AXIS = "hvd"


class ReduceOp(enum.IntEnum):
    """Reduction ops, value-compatible with the reference's hvd module consts

    (``horovod/torch/mpi_ops.py``: Average=0, Sum=1, Adasum=2, Min=3, Max=4,
    Product=5).
    """
    AVERAGE = 0
    SUM = 1
    ADASUM = 2
    MIN = 3
    MAX = 4
    PRODUCT = 5


# Module-level aliases matching `hvd.Average` etc.
Average = ReduceOp.AVERAGE
Sum = ReduceOp.SUM
Adasum = ReduceOp.ADASUM
Min = ReduceOp.MIN
Max = ReduceOp.MAX
Product = ReduceOp.PRODUCT


def axis_size(axis_name: AxisName = DEFAULT_AXIS):
    return compat_axis_size(axis_name)


def axis_rank(axis_name: AxisName = DEFAULT_AXIS):
    """This shard's index along the axis — the in-graph ``rank()``."""
    return lax.axis_index(axis_name)


def _scale(x, factor):
    if factor is None or factor == 1.0:
        return x
    # Keep scaling in the tensor dtype when safe; upcast low-precision ints.
    if jnp.issubdtype(x.dtype, jnp.integer):
        return (x.astype(jnp.float32) * factor).astype(x.dtype)
    return x * jnp.asarray(factor, dtype=x.dtype)


def allreduce(x, op: ReduceOp = ReduceOp.AVERAGE,
              axis_name: AxisName = DEFAULT_AXIS,
              prescale_factor: Optional[float] = None,
              postscale_factor: Optional[float] = None):
    """Allreduce of ``x`` over the axis.

    Parity: ``hvd.allreduce`` (reference ``horovod/torch/mpi_ops.py`` /
    ``horovod/tensorflow/mpi_ops.py``), incl. pre/post-scale factors
    (the reference fuses these as a CUDA scale kernel, N18; XLA fuses the
    multiply into the collective's producer/consumer for free).
    """
    x = _scale(x, prescale_factor)
    if op in (ReduceOp.AVERAGE, ReduceOp.SUM):
        out = lax.psum(x, axis_name)
        if op == ReduceOp.AVERAGE:
            n = compat_axis_size(axis_name)
            out = out / jnp.asarray(n, dtype=out.dtype) if jnp.issubdtype(
                out.dtype, jnp.floating) else out // n
    elif op == ReduceOp.MIN:
        out = lax.pmin(x, axis_name)
    elif op == ReduceOp.MAX:
        out = lax.pmax(x, axis_name)
    elif op == ReduceOp.PRODUCT:
        # No native pprod; exp/log is lossy — use all_gather+prod reduction.
        g = lax.all_gather(x, axis_name)
        out = jnp.prod(g, axis=0)
    elif op == ReduceOp.ADASUM:
        from ..parallel.adasum import adasum_allreduce
        out = adasum_allreduce(x, axis_name)
    else:
        raise ValueError(f"Unknown ReduceOp: {op}")
    return _scale(out, postscale_factor)


def grouped_allreduce(xs, op: ReduceOp = ReduceOp.AVERAGE,
                      axis_name: AxisName = DEFAULT_AXIS,
                      prescale_factor: Optional[float] = None,
                      postscale_factor: Optional[float] = None):
    """Allreduce a list of tensors as one atomic group.

    Parity: ``hvd.grouped_allreduce`` (reference group_table N13).  Under
    jit, passing the whole list to one ``psum`` lets XLA combine them into a
    single fused collective — the compiler-native version of the reference's
    fusion buffer.
    """
    xs = [_scale(x, prescale_factor) for x in xs]
    if op in (ReduceOp.AVERAGE, ReduceOp.SUM):
        outs = lax.psum(tuple(xs), axis_name)
        if op == ReduceOp.AVERAGE:
            n = compat_axis_size(axis_name)
            outs = tuple(o / jnp.asarray(n, o.dtype) for o in outs)
    else:
        outs = tuple(allreduce(x, op=op, axis_name=axis_name) for x in xs)
    return [_scale(o, postscale_factor) for o in outs]


def allgather(x, axis_name: AxisName = DEFAULT_AXIS, axis: int = 0,
              tiled: bool = True):
    """Gather shards from all ranks, concatenated along ``axis``.

    Parity: ``hvd.allgather`` — the reference concatenates along dim 0 and
    supports ragged first dims (handled in the eager layer by padding;
    in-graph shapes are static and must match).
    """
    return lax.all_gather(x, axis_name, axis=axis, tiled=tiled)


def broadcast(x, root_rank: int = 0, axis_name: AxisName = DEFAULT_AXIS):
    """Every rank receives rank ``root_rank``'s value.

    Parity: ``hvd.broadcast``.  Implemented as a masked psum, which XLA
    lowers to an efficient collective-broadcast on TPU.
    """
    idx = lax.axis_index(axis_name)
    mask = (idx == root_rank)
    if jnp.issubdtype(x.dtype, jnp.bool_):
        masked = jnp.where(mask, x, False)
        return lax.psum(masked.astype(jnp.int32), axis_name).astype(jnp.bool_)
    masked = jnp.where(mask, x, jnp.zeros_like(x))
    return lax.psum(masked, axis_name)


def alltoall(x, axis_name: AxisName = DEFAULT_AXIS,
             split_axis: int = 0, concat_axis: int = 0):
    """Even all-to-all: split ``x`` along ``split_axis`` into ``size`` chunks,
    exchange, concatenate received chunks along ``concat_axis``.

    Parity: ``hvd.alltoall`` with uniform splits (the DLRM embedding-exchange
    primitive, BASELINE config #5).  Ragged splits are an eager-layer feature
    (``horovod_tpu.alltoall`` pads to the max split in-graph).
    """
    return lax.all_to_all(x, axis_name, split_axis=split_axis,
                          concat_axis=concat_axis, tiled=True)


def reducescatter(x, op: ReduceOp = ReduceOp.SUM,
                  axis_name: AxisName = DEFAULT_AXIS, axis: int = 0):
    """Reduce across ranks and scatter shards along ``axis``.

    Parity: ``hvd.reducescatter`` (reference v0.28 ops, SURVEY.md §2c).
    The enabling primitive for ZeRO-style sharded optimizers
    (``horovod_tpu/parallel/zero.py``).
    """
    if op not in (ReduceOp.SUM, ReduceOp.AVERAGE):
        raise ValueError("reducescatter supports SUM and AVERAGE")
    out = lax.psum_scatter(x, axis_name, scatter_dimension=axis, tiled=True)
    if op == ReduceOp.AVERAGE:
        out = out / jnp.asarray(compat_axis_size(axis_name), out.dtype)
    return out


def ppermute(x, perm, axis_name: AxisName = DEFAULT_AXIS):
    """Point-to-point ring permute — the ring-attention substrate.

    No direct reference analogue (Horovod lacks SP, SURVEY.md §5); exposed
    because XLA's collective-permute over ICI is the natural primitive for
    ring collectives on the torus.
    """
    return lax.ppermute(x, axis_name, perm=perm)


def neighbor_shift(x, shift: int = 1, axis_name: AxisName = DEFAULT_AXIS):
    """Shift values around the ring by ``shift`` positions (wrapping)."""
    n = compat_axis_size(axis_name)
    perm = [(i, (i + shift) % n) for i in range(n)]
    return lax.ppermute(x, axis_name, perm=perm)


def barrier_value(axis_name: AxisName = DEFAULT_AXIS):
    """A value-level barrier: psum of 1 — all ranks must participate.

    Parity: ``hvd.barrier``.
    """
    return lax.psum(jnp.ones((), jnp.int32), axis_name)
