"""Data-plane scheduling primitives (no jax imports).

The pieces of the collective engine that are pure host-side scheduling —
the pending-tensor queue, the compiled-program cache, the stall inspector,
the in-flight dispatch window, the tensor partition plan and the
double-buffer staging slots — live here so the scheduler logic is
unit-testable without touching a jax backend (the fast test tier drives
these classes directly; ``ops/engine.py`` composes them with the XLA data
plane).

Reference mapping (SURVEY.md §2a): ``TensorQueue`` ← tensor_queue.cc N6,
``FusedProgramCache`` ← fusion_buffer_cache.cc N7 (as a compiled-executable
cache), ``StallInspector`` ← stall inspector N11, ``InflightRing`` ← the
in-flight response window ByteScheduler-style schedulers bound (Peng et
al., SOSP 2019) — here a bounded ring between the dispatching cycle thread
and a completion watcher.  ``partition_plan`` and ``PingPongBuffers`` are
the latency-war half (ISSUE 8): ByteScheduler-style tensor partitioning
and the double-buffered fusion staging handoff.
"""

from __future__ import annotations

import heapq
import threading
import time
from collections import deque
from typing import Callable, Dict, List, Optional, Sequence, Tuple

from ..utils.logging import get_logger

log = get_logger()

# Dispatch-backlog lanes (the heap orders by ``(lane, -priority, seq)``).
# 0 = latency fast lane, 1 = parameter-prefetch allgathers (ISSUE 18:
# FSDP's gather-on-demand legs — the NEXT forward pass blocks on them, so
# they sort ahead of the gradient drain, which only the step after needs),
# 2 = fused gradient batches, 3 = the checkpoint stream (ISSUE 14):
# checkpoint chunks sort strictly AFTER every gradient batch and are
# popped by their own budget, so durability I/O rides each cycle's tail
# without ever delaying (or re-ordering) gradient dispatch.  PREFETCH is
# budget-exempt like FAST: its presence can never change WHICH fused
# batches a cycle dispatches, nor their relative order (pinned by the
# prefetch-lane scheduler tests).
FAST_LANE = 0
PREFETCH_LANE = 1
FUSED_LANE = 2
CKPT_LANE = 3


class CheckpointChunk:
    """One checkpoint-lane work item (ISSUE 14): a bounded local write —
    one chunk of this rank's 1/N state shard — scheduled through the
    priority dispatch backlog at :data:`CKPT_LANE`.  Not a collective:
    it never negotiates, costs zero control-plane bytes, and its dispatch
    order is invisible to the gradient lanes.  ``run`` performs the
    chunk (the state plane owns retries/finalize inside it); ``fail`` is
    the abort path — the engine settles the lane with the fault and the
    epoch is abandoned, leaving the previous durable epoch in place."""

    __slots__ = ("name", "priority", "_run", "_fail")

    def __init__(self, name: str, run: Callable[[], None],
                 fail: Optional[Callable] = None, priority: int = 0):
        self.name = name
        self.priority = int(priority)
        self._run = run
        self._fail = fail

    def run(self) -> None:
        self._run()

    def fail(self, exc: BaseException) -> None:
        if self._fail is not None:
            self._fail(exc)


def pop_gradient_batches(heap: List[tuple], budget: int) -> List:
    """Pop the cycle's dispatchable batches from the backlog heap, in
    dispatch order: every fast-lane batch, every parameter-prefetch batch
    (ISSUE 18 — the gathers the NEXT forward pass blocks on), plus up to
    ``budget`` fused batches.  EXACTLY the pre-checkpoint-lane budget
    rule — a pure function of knob + heap state, never of checkpoint-lane
    occupancy: checkpoint items are never popped here and never consume
    the fused budget, so arming checkpointing cannot change gradient
    dispatch order (the heap sorts ``CKPT_LANE`` after every dispatch
    lane, so the guard only ever triggers once no gradient work remains).
    PREFETCH batches are likewise budget-exempt: arming parameter
    prefetch inserts gathers AHEAD of the fused drain but never changes
    which fused batches pop this cycle or their relative order — the
    invariant the prefetch-lane scheduler tests pin."""
    out: List = []
    while heap and heap[0][0] != CKPT_LANE \
            and (heap[0][0] != FUSED_LANE or budget > 0):
        if heap[0][0] == FUSED_LANE:
            budget -= 1
        out.append(heapq.heappop(heap)[3])
    return out


def pop_checkpoint_items(heap: List[tuple], budget: int) -> List:
    """Pop up to ``budget`` checkpoint-lane items — callable only once
    the gradient lanes are drained (the heap ordering enforces it: the
    head is ``CKPT_LANE`` exactly when no gradient batch remains)."""
    out: List = []
    while heap and heap[0][0] == CKPT_LANE and budget > 0:
        out.append(heapq.heappop(heap)[3])
        budget -= 1
    return out


def partition_plan(n_elems: int, itemsize: int,
                   threshold_bytes: int) -> Tuple[Tuple[int, int], ...]:
    """Even ``(offset, length)`` split of a flattened per-rank buffer into
    ~threshold-sized sub-tensors (ByteScheduler partitioning, Peng et al.
    SOSP 2019: the *partition*, not the fused batch, is the preemption
    unit — a huge gradient split into parts lets a small high-priority
    tensor jump the dispatch queue between parts instead of waiting out
    the whole transfer).

    A pure function of (element count, itemsize, threshold): every rank
    computes the identical plan from the negotiated shape/dtype, so the
    sub-tensor names and shapes — which ARE announced — agree across
    ranks.  Returns ``()`` when no split applies (threshold off, or the
    buffer already fits), never a 1-part plan."""
    total = n_elems * itemsize
    if threshold_bytes <= 0 or n_elems <= 1 or total <= threshold_bytes:
        return ()
    parts = -(-total // threshold_bytes)          # ceil
    parts = min(parts, n_elems)
    if parts <= 1:
        return ()
    per = -(-n_elems // parts)                    # ceil; last part shorter
    plan = []
    off = 0
    while off < n_elems:
        ln = min(per, n_elems - off)
        plan.append((off, ln))
        off += ln
    return tuple(plan)


def partition_name(parent: str, index: int, count: int) -> str:
    """Wire name of one sub-tensor.  Deterministic across ranks (the parts
    are negotiated under these names); ``parent_of`` inverts it."""
    return f"{parent}::part{index}/{count}"


def parent_of(name: str) -> str:
    """The parent tensor name behind a partition sub-name (identity for
    ordinary names)."""
    return name.rsplit("::part", 1)[0] if "::part" in name else name


class TensorQueue:
    """Thread-safe queue of pending entries (reference: tensor_queue.cc N6).

    Duplicate-name detection mirrors the reference's error on submitting a
    tensor name twice before completion.

    **Priority drain**: entries carry an integer ``priority`` (default 0);
    ``drain()`` returns higher priorities first, *stable within equal
    priority* (arrival order).  The DistributedOptimizer bindings stamp
    gradients with reverse-registration priority so the tensors the next
    forward pass needs first lead each cycle (the ByteScheduler insight:
    layer-0 grads arrive last from backprop but are needed first).
    Priorities must be stamped identically on every rank — like names,
    they are part of the deterministic announce order.
    """

    def __init__(self):
        self._lock = threading.Lock()
        self._entries: List = []
        self._pending_names: Dict[str, int] = {}

    def push(self, e):
        self.push_many([e])

    def push_many(self, entries: Sequence):
        """Atomic multi-entry push: a drain observes all or none — grouped
        ops rely on this so members always negotiate in the same round
        (reference: group_table N13 registers whole groups)."""
        with self._lock:
            seen = set()
            for e in entries:
                if e.name in self._pending_names or e.name in seen:
                    raise ValueError(
                        f"A tensor named {e.name!r} is already pending; "
                        f"Horovod semantics require unique names per "
                        f"in-flight collective")
                seen.add(e.name)
            now = time.monotonic()
            for e in entries:
                self._pending_names[e.name] = e.handle
                e.enqueue_time = now
                self._entries.append(e)

    def drain(self) -> List:
        with self._lock:
            out, self._entries = self._entries, []
        # Stable sort: equal priorities keep arrival order, so the default
        # (all zero) is byte-identical to the historical FIFO drain.
        out.sort(key=lambda e: -getattr(e, "priority", 0))
        return out

    def mark_done(self, e):
        with self._lock:
            self._pending_names.pop(e.name, None)

    def requeue(self, entries: Sequence):
        """Put drained-but-not-ready entries back for the next cycle
        (reference: ComputeResponseList re-queues tensors not yet ready on
        all ranks).  Names are still registered, so no duplicate check."""
        with self._lock:
            self._entries = list(entries) + self._entries

    def pending_count(self) -> int:
        with self._lock:
            return len(self._entries)


class FusedProgramCache:
    """Compiled fused-collective cache (the data-plane half of the steady-
    state fast path; the control-plane half is the controller's response
    cache).  Keyed on the *shape signature* of the batch (fusion key +
    shapes + dtypes + donation + wire compression + chunk counts — counts,
    never raw chunk byte values, so retuning ``HOROVOD_PIPELINE_CHUNK``
    only recompiles when the resulting chunk plan actually changes).  Hit
    == zero Python planning + zero XLA recompile: dispatch cost is one
    cached-executable launch.
    """

    def __init__(self, capacity: int = 1024):
        self.capacity = capacity
        self._cache: Dict[Tuple, Callable] = {}
        self.hits = 0
        self.misses = 0
        self.evictions = 0

    def __len__(self) -> int:
        return len(self._cache)

    def get_or_build(self, key: Tuple, builder: Callable[[], Callable]) -> Callable:
        fn, _ = self.get_or_build2(key, builder)
        return fn

    def get_or_build2(self, key: Tuple, builder: Callable[[], Callable]):
        """Returns ``(fn, hit)`` — hit=False means fn will compile on its
        first invocation (callers may scope compile-time-only handling)."""
        if self.capacity <= 0:
            # Caching disabled (HOROVOD_CACHE_CAPACITY=0): build every time.
            self.misses += 1
            return builder(), False
        fn = self._cache.get(key)
        if fn is None:
            self.misses += 1
            fn = builder()
            while len(self._cache) >= self.capacity:
                # LRU eviction (hits reinsert at the end of the dict order):
                # an A/B-alternating working set one entry over capacity
                # must not thrash the way FIFO would.
                self._cache.pop(next(iter(self._cache)))
                self.evictions += 1
            self._cache[key] = fn
            return fn, False
        # LRU touch: move to the end of the insertion order.
        self._cache.pop(key)
        self._cache[key] = fn
        self.hits += 1
        return fn, True


class StallInspector:
    """Warns when entries sit unexecuted too long (reference: N11).

    In single-controller mode entries execute next cycle, so stalls indicate
    an engine bug; in multi-process mode a stall names the ranks that have
    not submitted a tensor the others are waiting on — the reference's #1
    user-facing failure diagnosis (SURVEY.md §5 "race detection").
    """

    def __init__(self, warn_after_s: float, shutdown_after_s: float,
                 disabled: bool = False):
        self.warn_after_s = warn_after_s
        self.shutdown_after_s = shutdown_after_s
        self.disabled = disabled
        self._warned: set = set()
        # Names currently past the warn threshold — the live stall state
        # the monitor subsystem exports (/health, per-rank snapshots).
        # Unlike _warned (a log-once latch), this set empties the moment
        # the stalled collective completes.
        self.stalled: set = set()

    def check(self, waiting: Sequence,
              missing_ranks: Optional[Dict[str, List[int]]] = None):
        if self.disabled:
            return
        now = time.monotonic()
        # Partitioned sub-tensors (``e.partition = (parent, i, k)``) are
        # one logical collective to the user: collect them per parent and
        # report the PARENT once with partition progress, instead of k
        # near-duplicate HVD302 warnings for ``grad::part0/8``,
        # ``grad::part1/8``, ...
        part_groups: Dict[str, list] = {}
        for e in waiting:
            part = getattr(e, "partition", None)
            if part is not None:
                part_groups.setdefault(part[0], []).append(e)
                continue
            self._check_one(e, e.name, now, missing_ranks)
        for parent_name, group in part_groups.items():
            e = max(group, key=lambda g: now - g.enqueue_time)
            k = getattr(e, "partition")[2]
            settled = self._parts_settled(e, k)
            self._check_one(e, parent_name, now, missing_ranks,
                            partition=f" ({settled}/{k} parts settled)")

    @staticmethod
    def _parts_settled(e, k: int) -> int:
        """How many of a partitioned tensor's sub-entries already settled
        (duck-typed off the parent's part list; falls back to 0)."""
        parts = getattr(getattr(e, "parent", None), "parts", None)
        if not parts:
            return 0
        try:
            return sum(1 for s in parts if s.done.is_set())
        except Exception:  # noqa: BLE001 - progress is best-effort
            return 0

    def _check_one(self, e, report_name: str, now: float, missing_ranks,
                   partition: str = ""):
        age = now - e.enqueue_time
        if age > self.warn_after_s:
            self.stalled.add(report_name)
        if age > self.warn_after_s and report_name not in self._warned:
            self._warned.add(report_name)
            extra = ""
            if missing_ranks:
                missing = missing_ranks.get(e.name) \
                    or missing_ranks.get(report_name)
                if missing:
                    extra = f"; ranks not yet submitted: {missing}"
            # With tracing armed the entry carries a lifecycle span:
            # name the phase it is stuck in, not just that it waits.
            # Duck-typed: a dropped-claim sentinel has no phase_name.
            pn = getattr(getattr(e, "span", None), "phase_name", None)
            phase = f" (stuck in phase {pn()})" if pn else ""
            log.warning(
                "Stall detected: tensor %r has waited %.1fs for "
                "negotiation/execution%s%s%s", report_name, age, partition,
                phase, extra)
        if (self.shutdown_after_s > 0 and age > self.shutdown_after_s):
            raise RuntimeError(
                f"Collective on tensor {report_name!r} stalled for "
                f"{age:.1f}s (> HOROVOD_STALL_SHUTDOWN_TIME); aborting")

    def progressed(self, name: str):
        """A once-stalled tensor completed: clear its warned latch so a
        *later* collective reusing the name (steady-state training reuses
        gradient names every step) warns afresh instead of being silently
        swallowed by the first step's latch.  Partition sub-names clear
        the parent's latch too (the parent is what was warned about) —
        the next check re-warns with updated part progress."""
        self._warned.discard(name)
        self.stalled.discard(name)
        parent = parent_of(name)
        if parent != name:
            self._warned.discard(parent)
            self.stalled.discard(parent)


class InflightRing:
    """Bounded window of dispatched-but-unsettled fused batches.

    The cycle thread dispatches a fused program (an async XLA launch) and
    hands ``(batch, results)`` here instead of blocking on device results;
    the watcher thread waits for completion and settles the waiters
    (``e.done``) off the cycle thread, so host-side negotiation of cycle
    N+1 overlaps device execution of cycle N.  ``depth`` bounds how many
    batches may be in flight (``HOROVOD_MAX_INFLIGHT``); a full ring makes
    ``submit`` block — the back-pressure that keeps HBM from filling with
    queued fused buffers.  ``depth`` is runtime-tunable (autotune
    coordinate): shrinking simply delays the next submit until the window
    drains below the new bound.

    ``waiter(results)`` blocks until device results are real (the engine
    passes ``jax.block_until_ready``); ``settler(batch, results, error)``
    assigns results and releases waiters.  Both injectable, so the ring is
    testable without jax.
    """

    def __init__(self, waiter: Callable, settler: Callable, depth: int = 2):
        self.depth = max(1, int(depth))
        self._waiter = waiter
        self._settler = settler
        self._cv = threading.Condition()
        self._items: deque = deque()
        self._stop = False
        self._abort_error: Optional[BaseException] = None
        self.high_water = 0
        self.dispatched = 0
        self._thread = threading.Thread(
            target=self._watch, name="hvd-tpu-inflight", daemon=True)
        self._thread.start()

    def __len__(self) -> int:
        with self._cv:
            return len(self._items)

    def submit(self, batch, results):
        with self._cv:
            while len(self._items) >= max(1, self.depth) and not self._stop:
                self._cv.wait(0.1)
            error = self._abort_error
            if error is None:
                # [batch, results, settled]: the flag is the settle claim —
                # exactly one of watcher/abort flips it (under the lock)
                # and runs the settler for this batch.
                self._items.append([batch, results, False])
                self.dispatched += 1
                self.high_water = max(self.high_water, len(self._items))
                self._cv.notify_all()
                return
        # Aborted while (or before) waiting for a window slot: the watcher
        # may be wedged in a device wait that never returns — settle with
        # the fault here rather than queueing into a dead window.
        try:
            self._settler(batch, results, error)
        except BaseException:  # noqa: BLE001 - submit must not raise here
            log.exception("in-flight abort settle failed")

    def flush(self, timeout: Optional[float] = None) -> bool:
        """Block until every submitted batch has settled."""
        with self._cv:
            return self._cv.wait_for(lambda: not self._items, timeout)

    def stop(self):
        """Settle everything already submitted, then stop the watcher —
        waiters must never hang across an engine shutdown."""
        with self._cv:
            self._stop = True
            self._cv.notify_all()
        self._thread.join(timeout=10)

    def abort(self, error: BaseException):
        """Fail every queued batch with ``error`` WITHOUT waiting on device
        results, then stop accepting work.

        The control-plane fault path (a dead peer mid-negotiation): device
        results for already-dispatched batches may never materialize — a
        cross-process collective whose participant died can block forever —
        and the watcher itself may be wedged inside ``waiter`` on the head
        batch for exactly as long.  So the window is drained and settled
        HERE, on the aborting thread, including the batch the watcher is
        blocked on.  Each batch is settled by exactly one thread: the
        per-item claim flag is flipped under the lock, so a batch the
        watcher already settled SUCCESSFULLY is skipped — a completed
        collective must not retroactively report the fault.  A ``submit``
        racing the abort settles its batch with the fault instead of
        queueing it."""
        with self._cv:
            self._abort_error = error
            self._stop = True
            doomed = [it for it in self._items if not it[2]]
            for it in doomed:
                it[2] = True
            self._items.clear()
            self._cv.notify_all()
        for batch, results, _ in doomed:
            try:
                self._settler(batch, results, error)
            except BaseException:  # noqa: BLE001 - settle the rest anyway
                log.exception("in-flight abort settle failed")

    def _watch(self):
        while True:
            with self._cv:
                while not self._items and not self._stop:
                    self._cv.wait(0.2)
                if not self._items:
                    return          # stopped and drained
                head = self._items[0]
                batch, results = head[0], head[1]
                abort_error = self._abort_error
            error = None
            if abort_error is not None:
                # Control-plane abort: settle with the fault, never block
                # on device results that may not be coming.
                error = abort_error
            else:
                try:
                    self._waiter(results)
                except BaseException as exc:  # noqa: BLE001 - fail waiters
                    error = exc
            # Claim the settle atomically: if abort() got here first (it
            # can run while this thread is wedged in the device wait) the
            # batch is already settled with the fault — do not re-settle.
            with self._cv:
                claimed = not head[2]
                head[2] = True
            try:
                if claimed:
                    self._settler(batch, results, error)
            except BaseException:  # noqa: BLE001 - watcher must survive
                # A raising settler would otherwise kill this thread and
                # deadlock every later submit against a never-draining
                # window.  The settler owns waiter release; all the ring
                # can do is keep the pipeline alive and make the failure
                # visible.
                log.exception("in-flight settle failed")
            finally:
                # Pop AFTER settling so the window bounds dispatched-but-
                # unsettled work (a popped-then-settling batch would let
                # depth+1 launches pile up).
                with self._cv:
                    if self._items:
                        self._items.popleft()
                    self._cv.notify_all()


class StagingToken:
    """One acquired staging slot.  ``release`` is idempotent — exactly one
    of {normal settle, abort} actually frees the slot, the other is a
    no-op (mirrors the InflightRing's per-item settle claim)."""

    __slots__ = ("key", "slot", "_released")

    def __init__(self, key, slot: int):
        self.key = key
        self.slot = slot
        self._released = False


class PingPongBuffers:
    """Double-buffered fusion staging: two ownership slots per key (one
    key per fused-buffer dtype group).

    The cycle thread ``acquire``\\ s a slot before launching a fused batch
    and the InflightRing watcher ``release``\\ s it when the batch settles
    — so cycle N+1's copy_in (the host-side program fetch + async launch
    that stages the next fused buffer into HBM) may start while cycle N's
    reduce is still on the device, but cycle N+2's may not: at most two
    fused staging buffers per dtype group ever exist, regardless of how
    deep ``HOROVOD_MAX_INFLIGHT`` opens the ring.  That is the classic
    ping-pong buffer pair (reference N7's fusion-buffer reuse, pipelined),
    and it is what bounds fused-temporary HBM while the window is deep.

    ``abort`` settles every outstanding token exactly once (idempotent per
    token) and permanently opens the gate — once the control plane is
    down, no dispatcher may block on a slot the wedged watcher will never
    release.  jax-free: the fast test tier drives it directly."""

    def __init__(self, slots: int = 2):
        self.slots = max(1, int(slots))
        self._cv = threading.Condition()
        self._outstanding: Dict[object, List[StagingToken]] = {}
        self.aborted = False
        self.acquires = 0
        self.waits = 0            # acquires that had to block (telemetry)

    def in_flight(self, key) -> int:
        with self._cv:
            return len(self._outstanding.get(key, ()))

    def acquire(self, key) -> StagingToken:
        """Block until one of ``key``'s slots is free (or the pair is
        aborted); returns the slot's token."""
        with self._cv:
            waited = False
            while (not self.aborted
                   and len(self._outstanding.get(key, ())) >= self.slots):
                waited = True
                self._cv.wait(0.1)
            if waited:
                self.waits += 1
            self.acquires += 1
            used = {t.slot for t in self._outstanding.get(key, ())}
            slot = next(i for i in range(self.slots + 1) if i not in used)
            tok = StagingToken(key, slot)
            if not self.aborted:
                self._outstanding.setdefault(key, []).append(tok)
            else:
                # Aborted: hand out a pre-released token — the dispatch is
                # about to fail its entries anyway, and tracking it would
                # leak (nobody settles after abort).
                tok._released = True
            return tok

    def release(self, token: Optional[StagingToken]):
        if token is None:
            return
        with self._cv:
            if token._released:
                return                     # abort (or a double settle) won
            token._released = True
            lst = self._outstanding.get(token.key)
            if lst is not None:
                try:
                    lst.remove(token)
                except ValueError:
                    pass
                if not lst:
                    self._outstanding.pop(token.key, None)
            self._cv.notify_all()

    def abort(self):
        """Release every outstanding token exactly once and open the gate
        for good.  Idempotent; safe against concurrent release."""
        with self._cv:
            self.aborted = True
            for lst in self._outstanding.values():
                for tok in lst:
                    tok._released = True
            self._outstanding.clear()
            self._cv.notify_all()
