"""Data-plane scheduling primitives (no jax imports).

The pieces of the collective engine that are pure host-side scheduling —
the pending-tensor queue, the compiled-program cache, the stall inspector,
and the in-flight dispatch window — live here so the scheduler logic is
unit-testable without touching a jax backend (the fast test tier drives
these classes directly; ``ops/engine.py`` composes them with the XLA data
plane).

Reference mapping (SURVEY.md §2a): ``TensorQueue`` ← tensor_queue.cc N6,
``FusedProgramCache`` ← fusion_buffer_cache.cc N7 (as a compiled-executable
cache), ``StallInspector`` ← stall inspector N11, ``InflightRing`` ← the
in-flight response window ByteScheduler-style schedulers bound (Peng et
al., SOSP 2019) — here a bounded ring between the dispatching cycle thread
and a completion watcher.
"""

from __future__ import annotations

import threading
import time
from collections import deque
from typing import Callable, Dict, List, Optional, Sequence, Tuple

from ..utils.logging import get_logger

log = get_logger()


class TensorQueue:
    """Thread-safe queue of pending entries (reference: tensor_queue.cc N6).

    Duplicate-name detection mirrors the reference's error on submitting a
    tensor name twice before completion.

    **Priority drain**: entries carry an integer ``priority`` (default 0);
    ``drain()`` returns higher priorities first, *stable within equal
    priority* (arrival order).  The DistributedOptimizer bindings stamp
    gradients with reverse-registration priority so the tensors the next
    forward pass needs first lead each cycle (the ByteScheduler insight:
    layer-0 grads arrive last from backprop but are needed first).
    Priorities must be stamped identically on every rank — like names,
    they are part of the deterministic announce order.
    """

    def __init__(self):
        self._lock = threading.Lock()
        self._entries: List = []
        self._pending_names: Dict[str, int] = {}

    def push(self, e):
        self.push_many([e])

    def push_many(self, entries: Sequence):
        """Atomic multi-entry push: a drain observes all or none — grouped
        ops rely on this so members always negotiate in the same round
        (reference: group_table N13 registers whole groups)."""
        with self._lock:
            seen = set()
            for e in entries:
                if e.name in self._pending_names or e.name in seen:
                    raise ValueError(
                        f"A tensor named {e.name!r} is already pending; "
                        f"Horovod semantics require unique names per "
                        f"in-flight collective")
                seen.add(e.name)
            now = time.monotonic()
            for e in entries:
                self._pending_names[e.name] = e.handle
                e.enqueue_time = now
                self._entries.append(e)

    def drain(self) -> List:
        with self._lock:
            out, self._entries = self._entries, []
        # Stable sort: equal priorities keep arrival order, so the default
        # (all zero) is byte-identical to the historical FIFO drain.
        out.sort(key=lambda e: -getattr(e, "priority", 0))
        return out

    def mark_done(self, e):
        with self._lock:
            self._pending_names.pop(e.name, None)

    def requeue(self, entries: Sequence):
        """Put drained-but-not-ready entries back for the next cycle
        (reference: ComputeResponseList re-queues tensors not yet ready on
        all ranks).  Names are still registered, so no duplicate check."""
        with self._lock:
            self._entries = list(entries) + self._entries

    def pending_count(self) -> int:
        with self._lock:
            return len(self._entries)


class FusedProgramCache:
    """Compiled fused-collective cache (the data-plane half of the steady-
    state fast path; the control-plane half is the controller's response
    cache).  Keyed on the *shape signature* of the batch (fusion key +
    shapes + dtypes + donation + wire compression + chunk counts — counts,
    never raw chunk byte values, so retuning ``HOROVOD_PIPELINE_CHUNK``
    only recompiles when the resulting chunk plan actually changes).  Hit
    == zero Python planning + zero XLA recompile: dispatch cost is one
    cached-executable launch.
    """

    def __init__(self, capacity: int = 1024):
        self.capacity = capacity
        self._cache: Dict[Tuple, Callable] = {}
        self.hits = 0
        self.misses = 0
        self.evictions = 0

    def __len__(self) -> int:
        return len(self._cache)

    def get_or_build(self, key: Tuple, builder: Callable[[], Callable]) -> Callable:
        fn, _ = self.get_or_build2(key, builder)
        return fn

    def get_or_build2(self, key: Tuple, builder: Callable[[], Callable]):
        """Returns ``(fn, hit)`` — hit=False means fn will compile on its
        first invocation (callers may scope compile-time-only handling)."""
        if self.capacity <= 0:
            # Caching disabled (HOROVOD_CACHE_CAPACITY=0): build every time.
            self.misses += 1
            return builder(), False
        fn = self._cache.get(key)
        if fn is None:
            self.misses += 1
            fn = builder()
            while len(self._cache) >= self.capacity:
                # LRU eviction (hits reinsert at the end of the dict order):
                # an A/B-alternating working set one entry over capacity
                # must not thrash the way FIFO would.
                self._cache.pop(next(iter(self._cache)))
                self.evictions += 1
            self._cache[key] = fn
            return fn, False
        # LRU touch: move to the end of the insertion order.
        self._cache.pop(key)
        self._cache[key] = fn
        self.hits += 1
        return fn, True


class StallInspector:
    """Warns when entries sit unexecuted too long (reference: N11).

    In single-controller mode entries execute next cycle, so stalls indicate
    an engine bug; in multi-process mode a stall names the ranks that have
    not submitted a tensor the others are waiting on — the reference's #1
    user-facing failure diagnosis (SURVEY.md §5 "race detection").
    """

    def __init__(self, warn_after_s: float, shutdown_after_s: float,
                 disabled: bool = False):
        self.warn_after_s = warn_after_s
        self.shutdown_after_s = shutdown_after_s
        self.disabled = disabled
        self._warned: set = set()
        # Names currently past the warn threshold — the live stall state
        # the monitor subsystem exports (/health, per-rank snapshots).
        # Unlike _warned (a log-once latch), this set empties the moment
        # the stalled collective completes.
        self.stalled: set = set()

    def check(self, waiting: Sequence,
              missing_ranks: Optional[Dict[str, List[int]]] = None):
        if self.disabled:
            return
        now = time.monotonic()
        for e in waiting:
            age = now - e.enqueue_time
            if age > self.warn_after_s:
                self.stalled.add(e.name)
            if age > self.warn_after_s and e.name not in self._warned:
                self._warned.add(e.name)
                extra = ""
                if missing_ranks and e.name in missing_ranks:
                    extra = f"; ranks not yet submitted: {missing_ranks[e.name]}"
                # With tracing armed the entry carries a lifecycle span:
                # name the phase it is stuck in, not just that it waits.
                # Duck-typed: a dropped-claim sentinel has no phase_name.
                pn = getattr(getattr(e, "span", None), "phase_name", None)
                phase = f" (stuck in phase {pn()})" if pn else ""
                log.warning(
                    "Stall detected: tensor %r has waited %.1fs for "
                    "negotiation/execution%s%s", e.name, age, phase, extra)
            if (self.shutdown_after_s > 0 and age > self.shutdown_after_s):
                raise RuntimeError(
                    f"Collective on tensor {e.name!r} stalled for {age:.1f}s "
                    f"(> HOROVOD_STALL_SHUTDOWN_TIME); aborting")

    def progressed(self, name: str):
        """A once-stalled tensor completed: clear its warned latch so a
        *later* collective reusing the name (steady-state training reuses
        gradient names every step) warns afresh instead of being silently
        swallowed by the first step's latch."""
        self._warned.discard(name)
        self.stalled.discard(name)


class InflightRing:
    """Bounded window of dispatched-but-unsettled fused batches.

    The cycle thread dispatches a fused program (an async XLA launch) and
    hands ``(batch, results)`` here instead of blocking on device results;
    the watcher thread waits for completion and settles the waiters
    (``e.done``) off the cycle thread, so host-side negotiation of cycle
    N+1 overlaps device execution of cycle N.  ``depth`` bounds how many
    batches may be in flight (``HOROVOD_MAX_INFLIGHT``); a full ring makes
    ``submit`` block — the back-pressure that keeps HBM from filling with
    queued fused buffers.  ``depth`` is runtime-tunable (autotune
    coordinate): shrinking simply delays the next submit until the window
    drains below the new bound.

    ``waiter(results)`` blocks until device results are real (the engine
    passes ``jax.block_until_ready``); ``settler(batch, results, error)``
    assigns results and releases waiters.  Both injectable, so the ring is
    testable without jax.
    """

    def __init__(self, waiter: Callable, settler: Callable, depth: int = 2):
        self.depth = max(1, int(depth))
        self._waiter = waiter
        self._settler = settler
        self._cv = threading.Condition()
        self._items: deque = deque()
        self._stop = False
        self._abort_error: Optional[BaseException] = None
        self.high_water = 0
        self.dispatched = 0
        self._thread = threading.Thread(
            target=self._watch, name="hvd-tpu-inflight", daemon=True)
        self._thread.start()

    def __len__(self) -> int:
        with self._cv:
            return len(self._items)

    def submit(self, batch, results):
        with self._cv:
            while len(self._items) >= max(1, self.depth) and not self._stop:
                self._cv.wait(0.1)
            error = self._abort_error
            if error is None:
                # [batch, results, settled]: the flag is the settle claim —
                # exactly one of watcher/abort flips it (under the lock)
                # and runs the settler for this batch.
                self._items.append([batch, results, False])
                self.dispatched += 1
                self.high_water = max(self.high_water, len(self._items))
                self._cv.notify_all()
                return
        # Aborted while (or before) waiting for a window slot: the watcher
        # may be wedged in a device wait that never returns — settle with
        # the fault here rather than queueing into a dead window.
        try:
            self._settler(batch, results, error)
        except BaseException:  # noqa: BLE001 - submit must not raise here
            log.exception("in-flight abort settle failed")

    def flush(self, timeout: Optional[float] = None) -> bool:
        """Block until every submitted batch has settled."""
        with self._cv:
            return self._cv.wait_for(lambda: not self._items, timeout)

    def stop(self):
        """Settle everything already submitted, then stop the watcher —
        waiters must never hang across an engine shutdown."""
        with self._cv:
            self._stop = True
            self._cv.notify_all()
        self._thread.join(timeout=10)

    def abort(self, error: BaseException):
        """Fail every queued batch with ``error`` WITHOUT waiting on device
        results, then stop accepting work.

        The control-plane fault path (a dead peer mid-negotiation): device
        results for already-dispatched batches may never materialize — a
        cross-process collective whose participant died can block forever —
        and the watcher itself may be wedged inside ``waiter`` on the head
        batch for exactly as long.  So the window is drained and settled
        HERE, on the aborting thread, including the batch the watcher is
        blocked on.  Each batch is settled by exactly one thread: the
        per-item claim flag is flipped under the lock, so a batch the
        watcher already settled SUCCESSFULLY is skipped — a completed
        collective must not retroactively report the fault.  A ``submit``
        racing the abort settles its batch with the fault instead of
        queueing it."""
        with self._cv:
            self._abort_error = error
            self._stop = True
            doomed = [it for it in self._items if not it[2]]
            for it in doomed:
                it[2] = True
            self._items.clear()
            self._cv.notify_all()
        for batch, results, _ in doomed:
            try:
                self._settler(batch, results, error)
            except BaseException:  # noqa: BLE001 - settle the rest anyway
                log.exception("in-flight abort settle failed")

    def _watch(self):
        while True:
            with self._cv:
                while not self._items and not self._stop:
                    self._cv.wait(0.2)
                if not self._items:
                    return          # stopped and drained
                head = self._items[0]
                batch, results = head[0], head[1]
                abort_error = self._abort_error
            error = None
            if abort_error is not None:
                # Control-plane abort: settle with the fault, never block
                # on device results that may not be coming.
                error = abort_error
            else:
                try:
                    self._waiter(results)
                except BaseException as exc:  # noqa: BLE001 - fail waiters
                    error = exc
            # Claim the settle atomically: if abort() got here first (it
            # can run while this thread is wedged in the device wait) the
            # batch is already settled with the fault — do not re-settle.
            with self._cv:
                claimed = not head[2]
                head[2] = True
            try:
                if claimed:
                    self._settler(batch, results, error)
            except BaseException:  # noqa: BLE001 - watcher must survive
                # A raising settler would otherwise kill this thread and
                # deadlock every later submit against a never-draining
                # window.  The settler owns waiter release; all the ring
                # can do is keep the pipeline alive and make the failure
                # visible.
                log.exception("in-flight settle failed")
            finally:
                # Pop AFTER settling so the window bounds dispatched-but-
                # unsettled work (a popped-then-settling batch would let
                # depth+1 launches pile up).
                with self._cv:
                    if self._items:
                        self._items.popleft()
                    self._cv.notify_all()
