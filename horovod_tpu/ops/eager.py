"""Eager (out-of-graph) collective API — the ``hvd.*`` op surface.

Parity with the reference's Python op layer (``horovod/torch/mpi_ops.py``,
``horovod/tensorflow/mpi_ops.py`` — SURVEY.md §2b P2/P4): blocking and
``_async`` variants of allreduce / grouped_allreduce / allgather / broadcast /
alltoall / reducescatter, plus ``synchronize``/``poll``, ``barrier`` and
``join``.  Requests flow through the background coordinator
(``ops/engine.py``) exactly like the reference's enqueue path (SURVEY.md
§3.2), so fusion/caching/timeline apply.

Tensor convention (see engine docstring): per-rank logical shape S is carried
as a stacked global array ``[world, *S]`` sharded over the world axis.
``stack_per_rank`` / ``replicated`` build these from host data.
"""

from __future__ import annotations

import itertools
from typing import List, Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import NamedSharding, PartitionSpec as P

from . import collectives as C
from .engine import CollectiveType
from ..common import basics
from ..common.process_sets import ProcessSet

_name_counter = itertools.count(0)
_group_counter = itertools.count(0)

# Auto-generated collective names are part of the negotiation wire protocol:
# they must be identical on every rank.  init() resets all counters (every
# rank re-inits together on an elastic reset, so call-order counters
# realign); other modules with wire-visible counters register here.
_counter_reset_hooks: List = []


def register_name_counter_reset(fn):
    _counter_reset_hooks.append(fn)


def reset_name_counters():
    global _name_counter, _group_counter
    _name_counter = itertools.count(0)
    _group_counter = itertools.count(0)
    for fn in _counter_reset_hooks:
        fn()


def _engine():
    st = basics._get_state()
    if not st.initialized or st.engine is None:
        raise basics.NotInitializedError()
    return st.engine


def _ps(process_set: Optional[ProcessSet]) -> int:
    if process_set is None:
        return 0
    if process_set.process_set_id is None:
        raise ValueError("process_set has not been registered via add_process_set()")
    return process_set.process_set_id


def _auto_name(prefix: str, name: Optional[str]) -> str:
    return name if name else f"{prefix}.noname.{next(_name_counter)}"


def _wire_mode(compression) -> Optional[str]:
    """Normalize a ``compression=`` argument to an engine wire-dtype mode.

    Accepts ``None``/``"none"`` (off), ``"bf16"``/``"bfloat16"`` and
    ``"fp16"``/``"float16"``.  The framework bindings map their Compressor
    classes to these strings themselves (see jax/torch/tensorflow
    optimizers), so the cast pair fuses INTO the jitted collective program
    instead of running as separate host/device launches."""
    if compression is None:
        return None
    if hasattr(compression, "wire_mode"):
        # A Compressor class from any binding (the upstream calling
        # convention: compression=hvd.Compression.fp16).  Cast-style ones
        # carry their wire mode; NoneCompressor maps to off.
        return _wire_mode(compression.wire_mode)
    if isinstance(compression, str):
        c = compression.strip().lower()
        if c in ("", "none"):
            return None
        if c in ("fp16", "float16"):
            return "fp16"
        if c in ("bf16", "bfloat16"):
            return "bf16"
    raise ValueError(
        f"unsupported compression {compression!r}: expected None, 'none', "
        f"'fp16', 'bf16', or a Compression.* cast compressor")


def per_process_mode() -> bool:
    """True when this process contributes as ONE rank (torovodrun-launched,
    including an elastic world that currently has a single process) rather
    than controlling the whole world (single-controller SPMD)."""
    st = basics._get_state()
    topo = st.topology
    if topo is not None and topo.num_processes > 1:
        return True
    cfg = st.config
    return cfg is not None and cfg.controller_addr != ""


def _as_stacked(x, ps_id: int):
    """Coerce input to a stacked [world, *S] jax.Array on the set's mesh.

    Single-process mode: ``x`` is the full stacked [world, *S] host/device
    array.  Multi-process mode (launched by torovodrun): ``x`` is this
    process's LOCAL contribution — [*S] with one device per process, or
    [local_size, *S] with several — and the global array is assembled from
    per-device shards (``jax.make_array_from_single_device_arrays``), the
    TPU-native analogue of the reference's per-rank tensor submission
    (SURVEY.md §3.2).

    Device arrays stay device-resident: no ``np.asarray`` round-trip (the
    reference's fusion buffer exists to avoid exactly these host copies —
    SURVEY.md N7, §7 hard-part #2).

    Returns ``(array, owned)`` — ``owned`` is True when the array is a fresh
    temporary this layer created (safe for the engine to donate into the
    fused XLA program); False when it aliases the caller's array.
    """
    st = basics._get_state()
    ps = st.process_set_table.get(ps_id)
    world = ps.size()
    if isinstance(x, (np.ndarray, list, tuple, int, float)) or np.isscalar(x):
        x = np.asarray(x)
    sharding = NamedSharding(ps.mesh, P(ps.axis_name))
    if per_process_mode():
        if isinstance(x, jax.Array) and not x.is_fully_addressable:
            raise ValueError(
                "Multi-process eager collectives take this process's LOCAL "
                "contribution (a host array or local device array), not a "
                "global jax.Array; use hvd.to_local() on previous results "
                "before resubmitting them.")
        local_devs = [d for d in ps.mesh.devices.flat
                      if d.process_index == jax.process_index()]
        n_local = len(local_devs)
        device_resident = isinstance(x, jax.Array)
        if not device_resident:
            x = np.asarray(x)
        if n_local > 1:
            if x.shape[0] != n_local:
                raise ValueError(
                    f"Multi-device process: pass [local_size={n_local}, ...] "
                    f"local contributions; got {tuple(x.shape)}")
            per_dev = [x[i:i + 1] for i in range(n_local)]
        else:
            per_dev = [x[None] if not device_resident
                       else jnp.expand_dims(x, 0)]
        global_shape = (world,) + tuple(per_dev[0].shape[1:])
        shards = [jax.device_put(p, d) for p, d in zip(per_dev, local_devs)]
        return jax.make_array_from_single_device_arrays(
            global_shape, sharding, shards), True
    if hasattr(x, "shape") and (len(x.shape) == 0 or x.shape[0] != world):
        raise ValueError(
            f"Eager collectives take stacked per-rank tensors of shape "
            f"[world={world}, ...]; got shape {tuple(x.shape)}. Use "
            f"stack_per_rank()/replicated() to build one.")
    if isinstance(x, jax.Array):
        # Equivalent-sharding device_put ALIASES the input buffers rather
        # than copying, so donation would delete the caller's array — treat
        # any equivalently-sharded input as caller-owned.
        try:
            aliases = x.sharding.is_equivalent_to(sharding, x.ndim)
        except Exception:
            aliases = x.sharding == sharding
        if aliases:
            return (x if x.sharding == sharding
                    else jax.device_put(x, sharding)), False
    return jax.device_put(x, sharding), True


def to_global(tensor, process_set: Optional[ProcessSet] = None):
    """Assemble the stacked global ``[world, *S]`` array for this input.

    Single-process: accepts the full stacked array (host or device) and
    returns it placed on the world mesh.  Multi-process: accepts this
    process's LOCAL contribution (``[*S]``, or ``[local_size, *S]`` for a
    multi-device process) and returns the global array — the public
    counterpart of :func:`to_local` for feeding jitted/shard_map programs
    directly.
    """
    return _as_stacked(tensor, _ps(process_set))[0]


def to_local(result):
    """This process's view of a collective result.

    Replicated results (allreduce/broadcast/allgather) come back whole;
    stacked sharded results (alltoall/reducescatter) come back as this
    rank's slice(s).  Single-process mode returns the full array.
    """
    if not isinstance(result, jax.Array):
        return np.asarray(result)
    if jax.process_count() == 1 or result.is_fully_addressable:
        return np.asarray(result)
    # Dedupe by shard index: replicated results place the SAME full array on
    # every local device — concatenating duplicates would silently corrupt.
    by_index = {}
    for s in result.addressable_shards:
        by_index.setdefault(_index_key(s.index), s)
    shards = [by_index[k] for k in sorted(by_index)]
    datas = [np.asarray(s.data) for s in shards]
    if len(datas) == 1:
        return datas[0]
    return np.concatenate(datas, axis=0)


def _index_key(index):
    return tuple((sl.start if sl.start is not None else 0,
                  sl.stop if sl.stop is not None else -1)
                 for sl in index)


def stack_per_rank(values: Sequence, process_set: Optional[ProcessSet] = None):
    """Stack one value per rank into the collective input representation.

    Single-process: the full [world, *S] stacked array.  Multi-process: this
    process's slice (each process only holds its own ranks' contributions).
    """
    st = basics._get_state()
    ps = st.process_set_table.get(_ps(process_set))
    vals = [np.asarray(v) for v in values]
    if len(vals) != ps.size():
        raise ValueError(f"Expected {ps.size()} per-rank values, got {len(vals)}")
    stacked = np.stack(vals)
    if per_process_mode():
        my = [i for i, d in enumerate(ps.mesh.devices.flat)
              if d.process_index == jax.process_index()]
        local = stacked[my]
        return local[0] if len(my) == 1 else local
    return jax.device_put(stacked, NamedSharding(ps.mesh, P(ps.axis_name)))


def replicated(value, process_set: Optional[ProcessSet] = None):
    """Every rank contributes the same value."""
    st = basics._get_state()
    ps = st.process_set_table.get(_ps(process_set))
    v = np.asarray(value)
    return stack_per_rank([v] * ps.size(), process_set)


# ------------------------------------------------------------------ allreduce
def allreduce_async(tensor, name: Optional[str] = None,
                    op: C.ReduceOp = C.ReduceOp.AVERAGE,
                    prescale_factor: Optional[float] = None,
                    postscale_factor: Optional[float] = None,
                    process_set: Optional[ProcessSet] = None,
                    compression=None, priority: int = 0,
                    hierarchical: Optional[bool] = None) -> int:
    """``compression="bf16"``/``"fp16"`` casts floating tensors to the wire
    dtype inside the fused program (before the reduce) and back after —
    half the ICI bytes, zero extra launches, result in the input dtype.

    ``priority``: higher drains first from the coordinator queue (stable
    within equal priority).  Must be stamped identically on every rank —
    the DistributedOptimizer bindings use reverse registration order so
    first-needed gradients lead each cycle.

    ``hierarchical``: per-call override of the two-level ICI/DCN schedule
    (docs/performance.md "Hierarchical collectives") — True forces it,
    False forces flat, None (default) defers to
    HOROVOD_HIERARCHICAL_ALLREDUCE + the HOROVOD_HIER_THRESHOLD payload
    crossover.  Must be a rank-invariant constant (it forks the fused
    program shape; analyzer rule HVD110), but flipping it is free on the
    control plane — it rides the fusion key, never the digest."""
    ps_id = _ps(process_set)
    arr, owned = _as_stacked(tensor, ps_id)
    return _engine().enqueue(
        _auto_name("allreduce", name), CollectiveType.ALLREDUCE,
        arr, reduce_op=op, process_set_id=ps_id,
        prescale_factor=prescale_factor, postscale_factor=postscale_factor,
        donate=owned, compression=_wire_mode(compression),
        priority=priority, hierarchical=hierarchical)


def _sync_now(handle):
    """Blocking-op epilogue: kick the engine (inline cycle in
    single-controller mode — the small-tensor latency fast path) and wait."""
    _engine().kick()
    return synchronize(handle)


def allreduce(tensor, name: Optional[str] = None,
              op: C.ReduceOp = C.ReduceOp.AVERAGE,
              prescale_factor: Optional[float] = None,
              postscale_factor: Optional[float] = None,
              process_set: Optional[ProcessSet] = None,
              compression=None, priority: int = 0,
              hierarchical: Optional[bool] = None):
    return _sync_now(allreduce_async(
        tensor, name, op, prescale_factor, postscale_factor, process_set,
        compression, priority, hierarchical))


def grouped_allreduce_async(tensors: Sequence, name: Optional[str] = None,
                            op: C.ReduceOp = C.ReduceOp.AVERAGE,
                            prescale_factor: Optional[float] = None,
                            postscale_factor: Optional[float] = None,
                            process_set: Optional[ProcessSet] = None,
                            compression=None,
                            priorities: Optional[Sequence[int]] = None,
                            hierarchical: Optional[bool] = None
                            ) -> List[int]:
    """Enqueue a group that fuses/executes atomically (reference: N13).

    ``priorities`` (one int per tensor, same on every rank): drain
    priority per member — the group still executes atomically, but its
    position among OTHER clusters in the cycle follows its members'
    priorities."""
    ps_id = _ps(process_set)
    comp = _wire_mode(compression)
    gid = next(_group_counter)
    base = _auto_name("grouped_allreduce", name)
    if priorities is not None and len(priorities) != len(tensors):
        raise ValueError(
            f"priorities must have one entry per tensor: got "
            f"{len(priorities)} for {len(tensors)} tensors")
    items = []
    for i, t in enumerate(tensors):
        arr, owned = _as_stacked(t, ps_id)
        items.append(dict(
            name=f"{base}.{i}", ctype=CollectiveType.ALLREDUCE, tensor=arr,
            reduce_op=op, process_set_id=ps_id,
            prescale_factor=prescale_factor,
            postscale_factor=postscale_factor, group_id=gid, donate=owned,
            compression=comp,
            priority=int(priorities[i]) if priorities is not None else 0,
            hierarchical=hierarchical))
    # One atomic push: all members negotiate in the same round on every
    # rank, which both preserves fusion atomicity and lets a negotiation
    # error on one member abort the whole group (reference N13).
    return _engine().enqueue_group(items)


def grouped_allreduce(tensors: Sequence, name: Optional[str] = None,
                      op: C.ReduceOp = C.ReduceOp.AVERAGE,
                      prescale_factor: Optional[float] = None,
                      postscale_factor: Optional[float] = None,
                      process_set: Optional[ProcessSet] = None,
                      compression=None,
                      priorities: Optional[Sequence[int]] = None,
                      hierarchical: Optional[bool] = None):
    handles = grouped_allreduce_async(
        tensors, name, op, prescale_factor, postscale_factor, process_set,
        compression, priorities, hierarchical)
    _engine().kick()
    return [synchronize(h) for h in handles]


# ------------------------------------------------------------------ allgather
def allgather_async(tensor, name: Optional[str] = None,
                    process_set: Optional[ProcessSet] = None) -> int:
    ps_id = _ps(process_set)
    arr, owned = _as_stacked(tensor, ps_id)
    return _engine().enqueue(_auto_name("allgather", name),
                             CollectiveType.ALLGATHER,
                             arr, process_set_id=ps_id, donate=owned)


def allgather(tensor, name: Optional[str] = None,
              process_set: Optional[ProcessSet] = None):
    return _sync_now(allgather_async(tensor, name, process_set))


def _grouped_async(tensors, name, prefix, ctype, process_set,
                   priorities=None, **extra):
    """Shared grouped-enqueue core (reference N13 atomic groups): one
    atomic push, every member negotiates/batches together.

    ``priorities`` (one int per tensor, identical on every rank): drain
    priority per member, exactly like ``grouped_allreduce_async`` — the
    sharded optimizer stamps its reduce-scatter/allgather legs with
    reverse-registration order so first-needed parameters lead."""
    ps_id = _ps(process_set)
    gid = next(_group_counter)
    base = _auto_name(prefix, name)
    if priorities is not None and len(priorities) != len(tensors):
        raise ValueError(
            f"priorities must have one entry per tensor: got "
            f"{len(priorities)} for {len(tensors)} tensors")
    items = []
    for i, t in enumerate(tensors):
        arr, owned = _as_stacked(t, ps_id)
        items.append(dict(name=f"{base}.{i}", ctype=ctype, tensor=arr,
                          process_set_id=ps_id, group_id=gid, donate=owned,
                          priority=int(priorities[i])
                          if priorities is not None else 0,
                          **extra))
    return _engine().enqueue_group(items)


def grouped_allgather_async(tensors: Sequence, name: Optional[str] = None,
                            process_set: Optional[ProcessSet] = None,
                            priorities: Optional[Sequence[int]] = None,
                            sharded=False,
                            prefetch: bool = False) -> List[int]:
    """Reference: ``hvd.grouped_allgather`` (upstream v0.28).

    ``sharded=True`` marks the group as part of a ZeRO-sharded program
    (the allgather leg of reduce-scatter → shard update → allgather): the
    flag rides the fusion key AND the negotiation digest, so a sharded
    program can never cross-serve an unsharded collective of the same
    shapes (and divergence of the flag across ranks fails negotiation
    fast instead of executing mismatched programs).  ``sharded="full"``
    (ISSUE 18) is the FSDP plane's value — same properties, distinct
    digest token, so full-sharded programs can't cross-serve PR 15 ones.

    ``prefetch=True`` routes the group onto the engine's PREFETCH backlog
    lane (after FAST, before FUSED, budget-exempt): the FSDP optimizer
    marks the allgathers that rematerialize the next bucket's parameters
    so they launch ahead of — without reordering — the gradient stream.
    Fusion-key-only (not digest); must be rank-invariant (HVD110)."""
    return _grouped_async(tensors, name, "grouped_allgather",
                          CollectiveType.ALLGATHER, process_set,
                          priorities=priorities, sharded=sharded,
                          prefetch=prefetch)


def grouped_allgather(tensors: Sequence, name: Optional[str] = None,
                      process_set: Optional[ProcessSet] = None,
                      priorities: Optional[Sequence[int]] = None,
                      sharded=False, prefetch: bool = False):
    handles = grouped_allgather_async(tensors, name, process_set,
                                      priorities, sharded, prefetch)
    _engine().kick()
    return [synchronize(h) for h in handles]


def grouped_reducescatter_async(tensors: Sequence,
                                name: Optional[str] = None,
                                op: C.ReduceOp = C.ReduceOp.SUM,
                                process_set: Optional[ProcessSet] = None,
                                priorities: Optional[Sequence[int]] = None,
                                sharded=False) -> List[int]:
    """Reference: ``hvd.grouped_reducescatter`` (upstream v0.28).  See
    :func:`grouped_allgather_async` for ``priorities``/``sharded``
    (``sharded="full"`` marks the FSDP gradient reduce-scatter legs)."""
    return _grouped_async(tensors, name, "grouped_reducescatter",
                          CollectiveType.REDUCESCATTER, process_set,
                          reduce_op=op, priorities=priorities,
                          sharded=sharded)


def grouped_reducescatter(tensors: Sequence, name: Optional[str] = None,
                          op: C.ReduceOp = C.ReduceOp.SUM,
                          process_set: Optional[ProcessSet] = None,
                          priorities: Optional[Sequence[int]] = None,
                          sharded=False):
    handles = grouped_reducescatter_async(tensors, name, op, process_set,
                                          priorities, sharded)
    _engine().kick()
    return [synchronize(h) for h in handles]


# ------------------------------------------------------------------ broadcast
def broadcast_async(tensor, root_rank: int = 0, name: Optional[str] = None,
                    process_set: Optional[ProcessSet] = None) -> int:
    ps_id = _ps(process_set)
    arr, owned = _as_stacked(tensor, ps_id)
    return _engine().enqueue(_auto_name("broadcast", name),
                             CollectiveType.BROADCAST,
                             arr, root_rank=root_rank,
                             process_set_id=ps_id, donate=owned)


def broadcast(tensor, root_rank: int = 0, name: Optional[str] = None,
              process_set: Optional[ProcessSet] = None):
    return _sync_now(broadcast_async(tensor, root_rank, name, process_set))


def broadcast_pytree(tree, root_rank: int = 0,
                     process_set: Optional[ProcessSet] = None):
    """Broadcast every array leaf of a pytree from ``root_rank``; leaves come
    back as host arrays with their original dtype/shape.

    One async handle per leaf so the engine fuses them into few collectives
    (reference: ``broadcast_parameters``'s grouped broadcast)."""
    leaves, treedef = jax.tree_util.tree_flatten(tree)
    arrays = [np.asarray(l) for l in leaves]
    handles = [broadcast_async(
        a if per_process_mode() else replicated(a, process_set),
        root_rank=root_rank, name=f"bcast_pytree.{i}",
        process_set=process_set)
        for i, a in enumerate(arrays)]
    _engine().kick()     # one inline cycle fuses all leaves
    out = [np.asarray(to_local(synchronize(h))) for h in handles]
    out = [o.astype(a.dtype).reshape(a.shape) for o, a in zip(out, arrays)]
    return jax.tree_util.tree_unflatten(treedef, out)


def allgather_object(obj, name: Optional[str] = None,
                     process_set: Optional[ProcessSet] = None,
                     per_rank: Optional[bool] = None):
    """Pickle-allgather arbitrary per-rank objects (reference:
    ``horovod/torch/mpi_ops.py allgather_object``): returns the list of
    every rank's object, identical on all ranks.

    Multi-process mode: ``obj`` is THIS rank's object — or, for a process
    driving several local devices, a list with one object per local rank
    (like ``stack_per_rank``/the ragged alltoall).  Single-controller
    mode: a list with one object per rank, or a single object to
    replicate.

    ``per_rank`` disambiguates list payloads (where type-sniffing is
    otherwise the only signal): ``True`` means ``obj`` IS the list of
    per-rank objects this caller speaks for (``world`` entries in
    single-controller mode, ``n_local`` in a multi-device process);
    ``False`` means ``obj`` is ONE object contributed verbatim for every
    rank this caller speaks for — even when it happens to be a list of
    the magic length.  The default ``None`` keeps the legacy sniff.
    Portable scripts can pass ``per_rank=False`` under every launch mode.
    """
    import pickle
    st = basics._get_state()
    ps = st.process_set_table.get(_ps(process_set))
    world = ps.size()
    base = _auto_name("allgather_obj", name)
    if per_process_mode():
        n_local = len([d for d in ps.mesh.devices.flat
                       if d.process_index == jax.process_index()])
        if n_local > 1:
            if per_rank is False:
                objs = [obj] * n_local
            else:
                objs = list(obj) if isinstance(obj, (list, tuple)) else None
                if objs is None or len(objs) != n_local:
                    raise ValueError(
                        f"Multi-device process: pass a list of {n_local} "
                        f"per-local-rank objects (or per_rank=False to "
                        f"contribute one object for all local ranks)")
            payloads = [np.frombuffer(pickle.dumps(o), np.uint8)
                        for o in objs]
        else:
            if per_rank is True:
                if not isinstance(obj, (list, tuple)) or len(obj) != 1:
                    raise ValueError(
                        "per_rank=True in a single-device process: pass "
                        "a 1-list holding this rank's object")
                obj = obj[0]
            payloads = [np.frombuffer(pickle.dumps(obj), np.uint8)]
    else:
        if per_rank is True:
            if not isinstance(obj, (list, tuple)) or len(obj) != world:
                raise ValueError(
                    f"per_rank=True: expected a list of {world} per-rank "
                    f"objects, got "
                    f"{type(obj).__name__}"
                    + (f" of length {len(obj)}"
                       if isinstance(obj, (list, tuple)) else ""))
            objs = list(obj)
        elif per_rank is False:
            objs = [obj] * world
        else:
            objs = list(obj) if isinstance(obj, (list, tuple)) \
                else [obj] * world
            if len(objs) != world:
                raise ValueError(
                    f"Expected {world} per-rank objects, got {len(objs)} "
                    f"(pass per_rank=False to replicate a list payload "
                    f"verbatim)")
        payloads = [np.frombuffer(pickle.dumps(o), np.uint8) for o in objs]

    # Size prologue, then pad to max and ride ONE even allgather — the
    # same static-shape recipe as the ragged alltoall.  In multi-process
    # mode the local contribution is [*S] for one device or
    # [n_local, *S] rows for several, matching _as_stacked.
    multi_row = not per_process_mode() or len(payloads) > 1
    if multi_row:
        sz_in = np.stack([np.array([len(p)], np.int64) for p in payloads])
    else:
        sz_in = np.array([len(payloads[0])], np.int64)
    sizes = np.asarray(to_local(allgather(
        sz_in, name=f"{base}.sizes", process_set=process_set))).reshape(-1)
    m = max(1, int(sizes.max()))
    if multi_row:
        buf = np.zeros((len(payloads), m), np.uint8)
        for i, p in enumerate(payloads):
            buf[i, :len(p)] = p
    else:
        buf = np.zeros((m,), np.uint8)
        buf[:len(payloads[0])] = payloads[0]
    out = np.asarray(to_local(allgather(
        buf, name=f"{base}.payload", process_set=process_set)))
    out = out.reshape(world, m)
    return [pickle.loads(out[r, :int(sizes[r])].tobytes())
            for r in range(world)]


def broadcast_object(obj, root_rank: int = 0, name: Optional[str] = None,
                     process_set: Optional[ProcessSet] = None):
    """Pickle-broadcast an arbitrary Python object (reference:
    ``horovod/torch/functions.py broadcast_object``).

    In single-controller mode every rank already holds the object; the
    byte-level broadcast still runs so numerics/latency match multi-process.
    """
    import pickle
    st = basics._get_state()
    ps = st.process_set_table.get(_ps(process_set))
    payload = np.frombuffer(pickle.dumps(obj), dtype=np.uint8)
    n = np.array([len(payload)], dtype=np.int64)
    sizes = broadcast(stack_per_rank([n] * ps.size(), process_set),
                      root_rank=root_rank, name=_auto_name("bcast_obj_size", name))
    size = int(to_local(sizes)[0])
    buf = np.zeros(size, dtype=np.uint8)
    k = min(len(payload), size)
    buf[:k] = payload[:k]
    out = broadcast(stack_per_rank([buf] * ps.size(), process_set),
                    root_rank=root_rank, name=_auto_name("bcast_obj", name))
    return pickle.loads(to_local(out).tobytes())


# ------------------------------------------------------------------ alltoall
def alltoall_async(tensor, splits=None, name: Optional[str] = None,
                   process_set: Optional[ProcessSet] = None):
    """Async alltoall.  The even form returns an engine handle; the ragged
    form (``splits=...``) returns a two-stage continuation handle — the
    size-exchange allgather is already in flight when this returns, the
    padded payload alltoall is enqueued as soon as it lands (``poll`` or
    ``synchronize`` advance it), mirroring the reference where the whole
    exchange is async in the background thread."""
    if splits is not None:
        return _RaggedAlltoallHandle(tensor, splits,
                                     _auto_name("alltoallv", name),
                                     process_set)
    ps_id = _ps(process_set)
    arr, owned = _as_stacked(tensor, ps_id)
    return _engine().enqueue(_auto_name("alltoall", name),
                             CollectiveType.ALLTOALL,
                             arr, process_set_id=ps_id, donate=owned)


def alltoall(tensor, splits=None, name: Optional[str] = None,
             process_set: Optional[ProcessSet] = None):
    """Even alltoall returns the gathered rows; with ``splits`` (the ragged
    form, reference ``hvd.alltoall(tensor, splits)``) returns
    ``(output, received_splits)``."""
    return _sync_now(alltoall_async(tensor, splits, name, process_set))


def _pad_chunks(x, row, world: int, m: int):
    """[n_r, *inner] rows split per ``row`` → zero-padded [world*m, *inner]."""
    x = np.asarray(x)
    inner = x.shape[1:]
    out = np.zeros((world, m) + inner, x.dtype)
    off = 0
    for j in range(world):
        s = int(row[j])
        out[j, :s] = x[off:off + s]
        off += s
    if off != x.shape[0]:
        raise ValueError(
            f"splits sum to {off} but tensor has {x.shape[0]} rows")
    return out.reshape((world * m,) + inner)


class _RaggedAlltoallHandle:
    """Async continuation for uneven alltoall: size-exchange prologue,
    pad-to-max, ONE even engine alltoall, slice (reference:
    ``hvd.alltoall`` with splits / ``recv_splits`` — SURVEY.md §2c DLRM
    config #5; async capability per the reference's mpi_ops.cc alltoall).

    The send matrix is exchanged first (tiny allgather, already in flight
    when the constructor returns), making every per-destination chunk size
    static; the payload then rides the normal negotiated/fused
    even-alltoall with chunks padded to the max size, and receivers slice
    out the real rows.  Static shapes keep the compiled program cacheable
    across steps (DLRM splits are step-invariant).  ``poll``/``synchronize``
    advance the two-stage state machine; the result is
    ``(output, received_splits)`` — per-rank lists in single-controller
    mode (outputs are ragged and cannot stack).
    """

    def __init__(self, tensor, splits, base, process_set):
        self._ps_obj = process_set
        self._base = base
        ps_id = _ps(process_set)
        st = basics._get_state()
        ps = st.process_set_table.get(ps_id)
        self._world = world = ps.size()
        self._per_process = per_process_mode()
        self._result = None
        self._done = False

        if self._per_process:
            my_ranks = [i for i, d in enumerate(ps.mesh.devices.flat)
                        if d.process_index == jax.process_index()]
            self._my_ranks = my_ranks
            n_local = len(my_ranks)
            self._sp = np.asarray(splits, dtype=np.int64).reshape(
                n_local, world)
            if n_local > 1:
                # Per-local-rank rows are ragged too: a list of arrays.
                self._locals = [np.asarray(t) for t in tensor]
                if len(self._locals) != n_local:
                    raise ValueError(f"Multi-device process: pass a list of "
                                     f"{n_local} per-rank tensors")
            else:
                self._locals = [np.asarray(tensor)]
            # Size-exchange prologue: every rank's [world] splits row.
            sp_in = self._sp if n_local > 1 else self._sp[0]
            self._h_sizes = allgather_async(
                sp_in, name=f"{base}.splits", process_set=process_set)
            self._h_payload = None
        else:
            # Single-controller mode: ``splits`` is already the full
            # [world, world] matrix — no size exchange; payload goes out
            # immediately.
            tensors = (list(tensor) if isinstance(tensor, (list, tuple))
                       else [np.asarray(tensor)[r] for r in range(world)])
            if len(tensors) != world:
                raise ValueError(f"Expected {world} per-rank tensors, got "
                                 f"{len(tensors)}")
            self._send = np.asarray(splits, dtype=np.int64).reshape(
                world, world)
            self._m = max(1, int(self._send.max()))
            padded = np.stack(
                [_pad_chunks(tensors[r], self._send[r], world, self._m)
                 for r in range(world)])
            self._h_sizes = None
            self._h_payload = alltoall_async(
                padded, name=f"{base}.payload", process_set=process_set)

    def _start_payload(self, sizes_result):
        world, n_local = self._world, len(self._my_ranks)
        self._send = np.asarray(to_local(sizes_result)).reshape(world, world)
        self._m = max(1, int(self._send.max()))
        inner = self._locals[0].shape[1:]
        self._inner = inner
        padded = np.stack([_pad_chunks(self._locals[i], self._sp[i],
                                       world, self._m)
                           for i in range(n_local)])
        payload = padded if n_local > 1 else padded[0]
        self._locals = None  # staged into the engine; free the host copy
        self._h_payload = alltoall_async(
            payload, name=f"{self._base}.payload", process_set=self._ps_obj)

    def _finish(self, res):
        world, m = self._world, self._m
        if not self._per_process:
            res = np.asarray(res)
            outs = [np.concatenate(
                [res[j, r * m: r * m + int(self._send[r, j])]
                 for r in range(world)], axis=0) for j in range(world)]
            self._result = (outs, self._send.T.copy())
        else:
            n_local = len(self._my_ranks)
            res = np.asarray(to_local(res)).reshape(
                (n_local, world * m) + self._inner)
            outs, rsplits = [], []
            for i, g in enumerate(self._my_ranks):
                rows = [res[i, r * m: r * m + int(self._send[r, g])]
                        for r in range(world)]
                outs.append(np.concatenate(rows, axis=0))
                rsplits.append(self._send[:, g].copy())
            if n_local == 1:
                self._result = (outs[0], rsplits[0])
            else:
                self._result = (outs, np.stack(rsplits))
        self._done = True

    def poll(self) -> bool:
        if self._done:
            return True
        eng = _engine()
        if self._h_payload is None:
            if not eng.poll(self._h_sizes):
                return False
            self._start_payload(eng.synchronize(self._h_sizes))
        if eng.poll(self._h_payload):
            self._finish(eng.synchronize(self._h_payload))
            return True
        return False

    def synchronize(self):
        if not self._done:
            eng = _engine()
            if self._h_payload is None:
                eng.kick()
                self._start_payload(eng.synchronize(self._h_sizes))
            eng.kick()
            self._finish(eng.synchronize(self._h_payload))
        return self._result




# -------------------------------------------------------------- reducescatter
def reducescatter_async(tensor, name: Optional[str] = None,
                        op: C.ReduceOp = C.ReduceOp.SUM,
                        process_set: Optional[ProcessSet] = None) -> int:
    ps_id = _ps(process_set)
    arr, owned = _as_stacked(tensor, ps_id)
    return _engine().enqueue(_auto_name("reducescatter", name),
                             CollectiveType.REDUCESCATTER,
                             arr, reduce_op=op,
                             process_set_id=ps_id, donate=owned)


def reducescatter(tensor, name: Optional[str] = None,
                  op: C.ReduceOp = C.ReduceOp.SUM,
                  process_set: Optional[ProcessSet] = None):
    return _sync_now(reducescatter_async(tensor, name, op, process_set))


# ------------------------------------------------------------------- control
def synchronize(handle):
    """Wait for handle(s); returns result(s) (reference: mpi_ops.synchronize)."""
    if isinstance(handle, (list, tuple)):
        return [synchronize(h) for h in handle]
    if isinstance(handle, _RaggedAlltoallHandle):
        return handle.synchronize()
    return _engine().synchronize(handle)


def poll(handle) -> bool:
    if isinstance(handle, _RaggedAlltoallHandle):
        return handle.poll()
    return _engine().poll(handle)


def barrier(process_set: Optional[ProcessSet] = None):
    """Block until all ranks reach the barrier (reference: hvd.barrier)."""
    ps_id = _ps(process_set)
    eng = _engine()
    h = eng.enqueue(_auto_name("barrier", None), CollectiveType.BARRIER,
                    None, process_set_id=ps_id)
    eng.kick()
    return eng.synchronize(h)


def join(timeout: Optional[float] = None) -> int:
    """Signal this rank is done submitting work (reference: hvd.join).

    Multi-process mode: this rank keeps participating in peers' world-level
    collectives with synthesized ZERO contributions (uneven final batches —
    the reference's join use case) until every rank has joined; returns the
    last rank to join.  In single-controller mode every rank joins
    simultaneously, so this drains the queue and returns size()-1.

    Contract: always returns the last joining rank (an ``int >= 0``) —
    never a sentinel.  If ``timeout`` expires before every rank joined,
    raises :class:`~horovod_tpu.common.exceptions.JoinTimeoutError` (a
    ``TimeoutError`` subclass); the join stays pending and may be waited
    on again.
    """
    eng = _engine()
    ctrl = eng.controller
    if ctrl is None:
        barrier()
        return basics.size() - 1
    ctrl.request_join()
    eng._wake.set()
    return ctrl.join_wait(timeout)
