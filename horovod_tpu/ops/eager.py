"""Eager (out-of-graph) collective API — the ``hvd.*`` op surface.

Parity with the reference's Python op layer (``horovod/torch/mpi_ops.py``,
``horovod/tensorflow/mpi_ops.py`` — SURVEY.md §2b P2/P4): blocking and
``_async`` variants of allreduce / grouped_allreduce / allgather / broadcast /
alltoall / reducescatter, plus ``synchronize``/``poll``, ``barrier`` and
``join``.  Requests flow through the background coordinator
(``ops/engine.py``) exactly like the reference's enqueue path (SURVEY.md
§3.2), so fusion/caching/timeline apply.

Tensor convention (see engine docstring): per-rank logical shape S is carried
as a stacked global array ``[world, *S]`` sharded over the world axis.
``stack_per_rank`` / ``replicated`` build these from host data.
"""

from __future__ import annotations

import itertools
from typing import List, Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import NamedSharding, PartitionSpec as P

from . import collectives as C
from .engine import CollectiveType
from ..common import basics
from ..common.process_sets import ProcessSet

_name_counter = itertools.count(0)
_group_counter = itertools.count(0)


def _engine():
    st = basics._get_state()
    if not st.initialized or st.engine is None:
        raise basics.NotInitializedError()
    return st.engine


def _ps(process_set: Optional[ProcessSet]) -> int:
    if process_set is None:
        return 0
    if process_set.process_set_id is None:
        raise ValueError("process_set has not been registered via add_process_set()")
    return process_set.process_set_id


def _auto_name(prefix: str, name: Optional[str]) -> str:
    return name if name else f"{prefix}.noname.{next(_name_counter)}"


def _as_stacked(x, ps_id: int):
    """Coerce input to a stacked [world, *S] jax.Array on the set's mesh."""
    st = basics._get_state()
    ps = st.process_set_table.get(ps_id)
    world = ps.size()
    if isinstance(x, (np.ndarray, list, tuple, int, float)) or np.isscalar(x):
        x = np.asarray(x)
    if hasattr(x, "shape") and (len(x.shape) == 0 or x.shape[0] != world):
        raise ValueError(
            f"Eager collectives take stacked per-rank tensors of shape "
            f"[world={world}, ...]; got shape {tuple(x.shape)}. Use "
            f"stack_per_rank()/replicated() to build one.")
    sharding = NamedSharding(ps.mesh, P(ps.axis_name))
    if isinstance(x, jax.Array) and x.sharding == sharding:
        return x
    return jax.device_put(x, sharding)


def stack_per_rank(values: Sequence, process_set: Optional[ProcessSet] = None):
    """Stack one value per rank into the global stacked representation."""
    st = basics._get_state()
    ps = st.process_set_table.get(_ps(process_set))
    vals = [np.asarray(v) for v in values]
    if len(vals) != ps.size():
        raise ValueError(f"Expected {ps.size()} per-rank values, got {len(vals)}")
    stacked = np.stack(vals)
    return jax.device_put(stacked, NamedSharding(ps.mesh, P(ps.axis_name)))


def replicated(value, process_set: Optional[ProcessSet] = None):
    """Every rank contributes the same value."""
    st = basics._get_state()
    ps = st.process_set_table.get(_ps(process_set))
    v = np.asarray(value)
    return stack_per_rank([v] * ps.size(), process_set)


# ------------------------------------------------------------------ allreduce
def allreduce_async(tensor, name: Optional[str] = None,
                    op: C.ReduceOp = C.ReduceOp.AVERAGE,
                    prescale_factor: Optional[float] = None,
                    postscale_factor: Optional[float] = None,
                    process_set: Optional[ProcessSet] = None) -> int:
    ps_id = _ps(process_set)
    return _engine().enqueue(
        _auto_name("allreduce", name), CollectiveType.ALLREDUCE,
        _as_stacked(tensor, ps_id), reduce_op=op, process_set_id=ps_id,
        prescale_factor=prescale_factor, postscale_factor=postscale_factor)


def allreduce(tensor, name: Optional[str] = None,
              op: C.ReduceOp = C.ReduceOp.AVERAGE,
              prescale_factor: Optional[float] = None,
              postscale_factor: Optional[float] = None,
              process_set: Optional[ProcessSet] = None):
    return synchronize(allreduce_async(
        tensor, name, op, prescale_factor, postscale_factor, process_set))


def grouped_allreduce_async(tensors: Sequence, name: Optional[str] = None,
                            op: C.ReduceOp = C.ReduceOp.AVERAGE,
                            prescale_factor: Optional[float] = None,
                            postscale_factor: Optional[float] = None,
                            process_set: Optional[ProcessSet] = None) -> List[int]:
    """Enqueue a group that fuses/executes atomically (reference: N13)."""
    ps_id = _ps(process_set)
    gid = next(_group_counter)
    base = _auto_name("grouped_allreduce", name)
    eng = _engine()
    return [eng.enqueue(f"{base}.{i}", CollectiveType.ALLREDUCE,
                        _as_stacked(t, ps_id), reduce_op=op,
                        process_set_id=ps_id, prescale_factor=prescale_factor,
                        postscale_factor=postscale_factor, group_id=gid)
            for i, t in enumerate(tensors)]


def grouped_allreduce(tensors: Sequence, name: Optional[str] = None,
                      op: C.ReduceOp = C.ReduceOp.AVERAGE,
                      prescale_factor: Optional[float] = None,
                      postscale_factor: Optional[float] = None,
                      process_set: Optional[ProcessSet] = None):
    return [synchronize(h) for h in grouped_allreduce_async(
        tensors, name, op, prescale_factor, postscale_factor, process_set)]


# ------------------------------------------------------------------ allgather
def allgather_async(tensor, name: Optional[str] = None,
                    process_set: Optional[ProcessSet] = None) -> int:
    ps_id = _ps(process_set)
    return _engine().enqueue(_auto_name("allgather", name),
                             CollectiveType.ALLGATHER,
                             _as_stacked(tensor, ps_id), process_set_id=ps_id)


def allgather(tensor, name: Optional[str] = None,
              process_set: Optional[ProcessSet] = None):
    return synchronize(allgather_async(tensor, name, process_set))


# ------------------------------------------------------------------ broadcast
def broadcast_async(tensor, root_rank: int = 0, name: Optional[str] = None,
                    process_set: Optional[ProcessSet] = None) -> int:
    ps_id = _ps(process_set)
    return _engine().enqueue(_auto_name("broadcast", name),
                             CollectiveType.BROADCAST,
                             _as_stacked(tensor, ps_id), root_rank=root_rank,
                             process_set_id=ps_id)


def broadcast(tensor, root_rank: int = 0, name: Optional[str] = None,
              process_set: Optional[ProcessSet] = None):
    return synchronize(broadcast_async(tensor, root_rank, name, process_set))


def broadcast_object(obj, root_rank: int = 0, name: Optional[str] = None,
                     process_set: Optional[ProcessSet] = None):
    """Pickle-broadcast an arbitrary Python object (reference:
    ``horovod/torch/functions.py broadcast_object``).

    In single-controller mode every rank already holds the object; the
    byte-level broadcast still runs so numerics/latency match multi-process.
    """
    import pickle
    st = basics._get_state()
    ps = st.process_set_table.get(_ps(process_set))
    payload = np.frombuffer(pickle.dumps(obj), dtype=np.uint8)
    n = np.array([len(payload)], dtype=np.int64)
    sizes = broadcast(stack_per_rank([n] * ps.size(), process_set),
                      root_rank=root_rank, name=_auto_name("bcast_obj_size", name))
    size = int(np.asarray(sizes)[0])
    buf = np.zeros(size, dtype=np.uint8)
    buf[:len(payload)] = payload[:size]
    out = broadcast(stack_per_rank([buf] * ps.size(), process_set),
                    root_rank=root_rank, name=_auto_name("bcast_obj", name))
    return pickle.loads(np.asarray(out).tobytes())


# ------------------------------------------------------------------ alltoall
def alltoall_async(tensor, splits=None, name: Optional[str] = None,
                   process_set: Optional[ProcessSet] = None) -> int:
    if splits is not None:
        raise NotImplementedError(
            "Ragged alltoall splits land with the uneven-split planner; "
            "even splits (splits=None) are supported")
    ps_id = _ps(process_set)
    return _engine().enqueue(_auto_name("alltoall", name),
                             CollectiveType.ALLTOALL,
                             _as_stacked(tensor, ps_id), process_set_id=ps_id)


def alltoall(tensor, splits=None, name: Optional[str] = None,
             process_set: Optional[ProcessSet] = None):
    return synchronize(alltoall_async(tensor, splits, name, process_set))


# -------------------------------------------------------------- reducescatter
def reducescatter_async(tensor, name: Optional[str] = None,
                        op: C.ReduceOp = C.ReduceOp.SUM,
                        process_set: Optional[ProcessSet] = None) -> int:
    ps_id = _ps(process_set)
    return _engine().enqueue(_auto_name("reducescatter", name),
                             CollectiveType.REDUCESCATTER,
                             _as_stacked(tensor, ps_id), reduce_op=op,
                             process_set_id=ps_id)


def reducescatter(tensor, name: Optional[str] = None,
                  op: C.ReduceOp = C.ReduceOp.SUM,
                  process_set: Optional[ProcessSet] = None):
    return synchronize(reducescatter_async(tensor, name, op, process_set))


# ------------------------------------------------------------------- control
def synchronize(handle):
    """Wait for handle(s); returns result(s) (reference: mpi_ops.synchronize)."""
    if isinstance(handle, (list, tuple)):
        return [_engine().synchronize(h) for h in handle]
    return _engine().synchronize(handle)


def poll(handle) -> bool:
    return _engine().poll(handle)


def barrier(process_set: Optional[ProcessSet] = None):
    """Block until all ranks reach the barrier (reference: hvd.barrier)."""
    ps_id = _ps(process_set)
    h = _engine().enqueue(_auto_name("barrier", None), CollectiveType.BARRIER,
                          None, process_set_id=ps_id)
    return _engine().synchronize(h)


def join() -> int:
    """Signal this rank is done submitting work (reference: hvd.join).

    Returns the last rank to join.  In single-controller mode every rank
    joins simultaneously, so this drains the queue and returns size()-1.
    """
    barrier()
    return basics.size() - 1
