"""Pallas TPU flash attention (forward + backward kernels).

The hot op of the flagship Llama path (SURVEY.md §7 "pallas kernels for the
hot ops"; no reference analogue — Horovod ships no model math).  Standard
flash attention: the [Tq, Tk] score matrix is never materialized in HBM;
each (batch·head, q-block) streams k/v blocks through VMEM with an
online-softmax accumulator.  The backward pass recomputes probabilities
blockwise from the saved logsumexp — two kernels (dq; dk/dv) so every
accumulator lives in VMEM scratch across the inner grid dimension.

Layout: ``[B, T, H, D]`` (the llama layout).  GQA is native: pass kv with
``K = H / rep`` heads and each q-head group reads its shared kv head
through the kernels' block index maps — the repeat never touches HBM.

On non-TPU backends the kernels run in Pallas interpret mode (tests), so
the same code path is exercised everywhere; ``models/llama`` routes to
this kernel on TPU and keeps the jnp reference elsewhere.
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from ..compat import tpu_compiler_params

NEG_INF = -1e30


def _interpret_default() -> bool:
    return jax.default_backend() != "tpu"


def resolve_flash(override: Optional[bool] = None,
                  seq: Optional[int] = None,
                  causal: bool = False) -> bool:
    """Config-first flash routing: a model config's ``use_flash`` field
    (traced, so toggling it recompiles) wins; ``None`` falls back to
    :func:`flash_enabled` with the caller's sequence length and
    causality."""
    return flash_enabled(seq, causal) if override is None else override


def _env_int(name: str, dflt: int, valid=lambda v: True) -> int:
    """Env-tunable integer knob: bad, unparseable, or out-of-contract
    values keep the default instead of dying at trace time."""
    import os
    try:
        v = int(os.environ.get(name, str(dflt)))
        return v if valid(v) else dflt
    except ValueError:
        return dflt


def flash_min_seq(causal: bool = False) -> int:
    """Auto-mode crossover, measured on real v5e (BENCH_SELF_r05, full
    in-model A/B with the raw-bf16 kernels and 512x512 tiles):

    - **causal** (llama family): flash already wins at T=512
      (623k vs 552k tok/s) — whole-block causal skipping halves the
      work, so the crossover default is 512.
    - **non-causal** (bert): XLA's fused attention wins at T=256
      (789k vs 649k tok/s — no blocks to skip, flash's rescaling
      machinery is pure overhead) and flash wins at T=1024 (544k vs
      424k), bracketing the crossover — the default stays 1024, now
      measured in-model on both sides.

    ``HVD_TPU_FLASH_MIN_SEQ`` overrides BOTH; tools/flash_sweep.py
    re-measures the crossover per chip."""
    return _env_int("HVD_TPU_FLASH_MIN_SEQ", 512 if causal else 1024,
                    lambda v: v >= 0)


def flash_enabled(seq: Optional[int] = None,
                  causal: bool = False) -> bool:
    """Shared routing default for attention call sites (llama, bert,
    Ulysses, ring): pallas flash on TPU for sequences past the measured
    crossover (:func:`flash_min_seq` — causality-aware), jnp reference
    elsewhere; ``HVD_TPU_FLASH=1/0`` forces it globally — all read at
    TRACE time only (not part of any jit cache key)."""
    import os
    v = os.environ.get("HVD_TPU_FLASH", "auto").lower()
    if v in ("1", "true", "on"):
        return True
    if v in ("0", "false", "off"):
        return False
    if jax.default_backend() != "tpu":
        return False
    return seq is None or seq >= flash_min_seq(causal)


# ----------------------------------------------------------------- forward
def _fwd_kernel(q_ref, k_ref, v_ref, o_ref, lse_ref,
                acc_ref, m_ref, l_ref, *, scale, causal, block_q, block_k,
                n_k, tk_valid, window):
    qi = pl.program_id(1)
    ki = pl.program_id(2)

    @pl.when(ki == 0)
    def _():
        m_ref[:] = jnp.full_like(m_ref, NEG_INF)
        l_ref[:] = jnp.zeros_like(l_ref)
        acc_ref[:] = jnp.zeros_like(acc_ref)

    q_start = qi * block_q
    k_start = ki * block_k
    # Causal: skip k-blocks strictly above the diagonal band; a sliding
    # window additionally skips blocks entirely BELOW the band (the
    # Mistral-style O(T·W) compute shape — whole blocks outside
    # [r-window+1, r] never touch the MXU).
    live = (not causal) or (k_start <= q_start + block_q - 1)
    if window:
        live = jnp.logical_and(live,
                               k_start + block_k > q_start - window)

    @pl.when(live)
    def _():
        # Dots take the RAW input dtype (bf16 in training) with an f32
        # accumulator: bf16×bf16 products are exact in f32 accumulation,
        # so this matches the old cast-to-f32-first numerics while running
        # the MXU at full bf16 rate instead of the ~4x-slower f32 path
        # (the measured BENCH_SELF_r05 flash regression).
        q = q_ref[0]                                # [bq, D]
        k = k_ref[0]                                # [bk, D]
        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32) * scale   # [bq, bk]
        cols = k_start + jax.lax.broadcasted_iota(
            jnp.int32, (block_q, block_k), 1)
        mask = cols < tk_valid
        if causal:
            rows = q_start + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 0)
            mask = jnp.logical_and(mask, rows >= cols)
            if window:
                mask = jnp.logical_and(mask, rows - cols < window)
        s = jnp.where(mask, s, NEG_INF)

        m_prev = m_ref[:]
        m_new = jnp.maximum(m_prev, jnp.max(s, axis=-1))
        p = jnp.exp(s - m_new[:, None])
        alpha = jnp.exp(m_prev - m_new)
        l_ref[:] = l_ref[:] * alpha + jnp.sum(p, axis=-1)
        # p is quantized to the value dtype for the second MXU pass (the
        # standard TPU flash formulation; exact when inputs are f32).
        acc_ref[:] = acc_ref[:] * alpha[:, None] + jax.lax.dot_general(
            p.astype(v_ref.dtype), v_ref[0], (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        m_ref[:] = m_new

    @pl.when(ki == n_k - 1)
    def _():
        l = l_ref[:]
        safe_l = jnp.where(l == 0.0, 1.0, l)
        o_ref[0] = (acc_ref[:] / safe_l[:, None]).astype(o_ref.dtype)
        # Empty rows (fully masked) store lse=0, NOT -inf: the backward
        # computes p = exp(s - lse) with s = NEG_INF on masked entries, and
        # exp(NEG_INF - 0) = 0 zeroes their contribution, while -inf would
        # turn it into exp(0) = 1 and poison dk/dv.
        lse_ref[0, :, 0] = jnp.where(l == 0.0, 0.0,
                                     m_ref[:] + jnp.log(safe_l))


# ---------------------------------------------------------------- backward
def _dq_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref, dq_ref,
               acc_ref, *, scale, causal, block_q, block_k, n_k,
               tq_valid, tk_valid, window):
    qi = pl.program_id(1)
    ki = pl.program_id(2)

    @pl.when(ki == 0)
    def _():
        acc_ref[:] = jnp.zeros_like(acc_ref)

    q_start = qi * block_q
    k_start = ki * block_k
    live = (not causal) or (k_start <= q_start + block_q - 1)
    if window:
        live = jnp.logical_and(live,
                               k_start + block_k > q_start - window)

    @pl.when(live)
    def _():
        # Raw-dtype MXU operands + f32 accumulators (see _fwd_kernel): the
        # f32 intermediates p/ds are quantized back to the operand dtype
        # for their second matmuls.
        q = q_ref[0]
        k = k_ref[0]
        v = v_ref[0]
        do = do_ref[0]
        s = jax.lax.dot_general(q, k, (((1,), (1,)), ((), ())),
                                preferred_element_type=jnp.float32) * scale
        cols = k_start + jax.lax.broadcasted_iota(
            jnp.int32, (block_q, block_k), 1)
        rows = q_start + jax.lax.broadcasted_iota(
            jnp.int32, (block_q, block_k), 0)
        mask = jnp.logical_and(cols < tk_valid, rows < tq_valid)
        if causal:
            mask = jnp.logical_and(mask, rows >= cols)
            if window:
                mask = jnp.logical_and(mask, rows - cols < window)
        s = jnp.where(mask, s, NEG_INF)
        p = jnp.exp(s - lse_ref[0, :, :1])        # [bq, bk]
        dp = jax.lax.dot_general(do, v, (((1,), (1,)), ((), ())),
                                 preferred_element_type=jnp.float32)
        ds = (p * (dp - delta_ref[0, :, :1]) * scale).astype(k.dtype)
        acc_ref[:] += jax.lax.dot_general(
            ds, k, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)

    @pl.when(ki == n_k - 1)
    def _():
        dq_ref[0] = acc_ref[:].astype(dq_ref.dtype)


def _dkv_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref,
                dk_ref, dv_ref, dk_acc, dv_acc, *, scale, causal,
                block_q, block_k, n_q, n_t, tq_valid, tk_valid, window):
    ki = pl.program_id(1)
    t = pl.program_id(2)      # = r * n_q + qi over the rep q-heads (GQA)
    qi = t % n_q

    @pl.when(t == 0)
    def _():
        dk_acc[:] = jnp.zeros_like(dk_acc)
        dv_acc[:] = jnp.zeros_like(dv_acc)

    q_start = qi * block_q
    k_start = ki * block_k
    live = (not causal) or (k_start <= q_start + block_q - 1)
    if window:
        live = jnp.logical_and(live,
                               k_start + block_k > q_start - window)

    @pl.when(live)
    def _():
        # Raw-dtype MXU operands + f32 accumulators (see _fwd_kernel).
        q = q_ref[0]
        k = k_ref[0]
        v = v_ref[0]
        do = do_ref[0]
        s = jax.lax.dot_general(q, k, (((1,), (1,)), ((), ())),
                                preferred_element_type=jnp.float32) * scale
        cols = k_start + jax.lax.broadcasted_iota(
            jnp.int32, (block_q, block_k), 1)
        rows = q_start + jax.lax.broadcasted_iota(
            jnp.int32, (block_q, block_k), 0)
        mask = jnp.logical_and(cols < tk_valid, rows < tq_valid)
        if causal:
            mask = jnp.logical_and(mask, rows >= cols)
            if window:
                mask = jnp.logical_and(mask, rows - cols < window)
        s = jnp.where(mask, s, NEG_INF)
        p = jnp.exp(s - lse_ref[0, :, :1])        # [bq, bk]
        dv_acc[:] += jax.lax.dot_general(
            p.astype(do.dtype), do, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)      # [bk, D]
        dp = jax.lax.dot_general(do, v, (((1,), (1,)), ((), ())),
                                 preferred_element_type=jnp.float32)
        ds = (p * (dp - delta_ref[0, :, :1]) * scale).astype(q.dtype)
        dk_acc[:] += jax.lax.dot_general(
            ds, q, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)      # [bk, D]

    @pl.when(t == n_t - 1)
    def _():
        dk_ref[0] = dk_acc[:].astype(dk_ref.dtype)
        dv_ref[0] = dv_acc[:].astype(dv_ref.dtype)


# -------------------------------------------------------------- dispatcher
def _pad_t(x, block):
    t = x.shape[1]
    pad = (-t) % block
    if pad:
        x = jnp.pad(x, ((0, 0), (0, pad), (0, 0)))
    return x


def _fwd_impl(q, k, v, scale, causal, block_q, block_k, interpret, rep=1,
              window=0):
    """q: [BH, T, D]; k, v: [BH // rep, T, D] (GQA: ``rep`` consecutive
    q-heads share one kv head — remapped in the BlockSpec index, no
    materialized repeat) -> (o [BH, Tq, D], lse [BH, Tq])."""
    BH, Tq, D = q.shape
    Tk = k.shape[1]
    bq, bk = min(block_q, Tq), min(block_k, Tk)
    qp, kp, vp = _pad_t(q, bq), _pad_t(k, bk), _pad_t(v, bk)
    Tqp, Tkp = qp.shape[1], kp.shape[1]
    n_q, n_k = Tqp // bq, Tkp // bk

    kern = functools.partial(_fwd_kernel, scale=scale, causal=causal,
                             block_q=bq, block_k=bk, n_k=n_k, tk_valid=Tk,
                             window=window)
    o, lse = pl.pallas_call(
        kern,
        grid=(BH, n_q, n_k),
        in_specs=[
            pl.BlockSpec((1, bq, D), lambda b, i, j: (b, i, 0)),
            pl.BlockSpec((1, bk, D), lambda b, i, j: (b // rep, j, 0)),
            pl.BlockSpec((1, bk, D), lambda b, i, j: (b // rep, j, 0)),
        ],
        out_specs=[
            pl.BlockSpec((1, bq, D), lambda b, i, j: (b, i, 0)),
            # 3D (1, bq, 1): TPU block rules need the trailing dims
            # divisible by (8, 128) or equal to the array's — a [BH, T]
            # row vector can't satisfy that, [BH, T, 1] can.
            pl.BlockSpec((1, bq, 1), lambda b, i, j: (b, i, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((BH, Tqp, D), q.dtype),
            jax.ShapeDtypeStruct((BH, Tqp, 1), jnp.float32),
        ],
        scratch_shapes=[
            pltpu.VMEM((bq, D), jnp.float32),
            pltpu.VMEM((bq,), jnp.float32),
            pltpu.VMEM((bq,), jnp.float32),
        ],
        compiler_params=tpu_compiler_params(
            dimension_semantics=("parallel", "parallel", "arbitrary")),
        interpret=interpret,
    )(qp, kp, vp)
    return o[:, :Tq], lse[:, :Tq, 0]


def _block_defaults() -> tuple:
    """Kernel tile defaults, env-overridable for per-chip tuning
    (``HVD_TPU_FLASH_BLOCK_Q`` / ``HVD_TPU_FLASH_BLOCK_K`` — read at
    trace time; tools/flash_sweep.py measures the candidates).  512x512
    won or tied every shape in the on-chip sweep (FLASH_SWEEP_r05.json:
    1.3-2.1x faster than the old 128x128 at T>=1024, 5x at T=8192 —
    bigger tiles amortize the grid/rescale overhead and keep the MXU
    fed).  The sublane rule (multiples of 8) is enforced here so a bad
    value keeps the default instead of dying in Mosaic lowering."""
    ok = lambda v: v >= 8 and v % 8 == 0  # noqa: E731
    return (_env_int("HVD_TPU_FLASH_BLOCK_Q", 512, ok),
            _env_int("HVD_TPU_FLASH_BLOCK_K", 512, ok))


def resolve_blocks(block_q: Optional[int],
                   block_k: Optional[int]) -> tuple:
    """Fill ``None`` tile sizes from :func:`_block_defaults` — the one
    resolution point shared by every flash call site (single-device,
    Ulysses, ring)."""
    dq, dk = _block_defaults()
    return (dq if block_q is None else block_q,
            dk if block_k is None else block_k)


def flash_attention(q, k, v, causal: bool = False,
                    scale: Optional[float] = None,
                    block_q: Optional[int] = None,
                    block_k: Optional[int] = None,
                    interpret: Optional[bool] = None,
                    window: Optional[int] = None):
    """Memory-efficient exact attention.

    q: ``[B, T, H, D]``; k, v: ``[B, T, K, D]`` with ``H % K == 0`` — GQA
    is native (each group of ``H // K`` consecutive q-heads reads its kv
    head through the kernel's block index map; the kv tensors are never
    repeated in HBM).  Differentiable via flash backward kernels; matches
    ``parallel.ring_attention.local_flash_attention`` numerically.
    """
    B, Tq, H, D = q.shape
    K = k.shape[2]
    if v.shape[2] != K:
        raise ValueError(f"k has {K} heads but v has {v.shape[2]}")
    if H % K:
        raise ValueError(f"q heads ({H}) must be a multiple of kv heads "
                         f"({K}) for GQA")
    if k.dtype != q.dtype or v.dtype != q.dtype:
        # The kernels feed RAW operands to the MXU (bf16 at full rate) —
        # mixed dtypes would die with a cryptic dot_general trace error.
        raise ValueError(f"q/k/v must share one dtype, got {q.dtype}/"
                         f"{k.dtype}/{v.dtype}; cast before the call")
    rep = H // K
    scale = scale if scale is not None else 1.0 / (D ** 0.5)
    block_q, block_k = resolve_blocks(block_q, block_k)
    interpret = _interpret_default() if interpret is None else interpret
    if window is not None:
        if not causal:
            raise ValueError("window (sliding-window attention) requires "
                             "causal=True")
        if window < 1:
            raise ValueError(f"window must be >= 1, got {window}")

    def to_bh(x):
        h = x.shape[2]
        return x.transpose(0, 2, 1, 3).reshape(B * h, x.shape[1], D)

    def from_bh(x, t):
        return x.reshape(B, H, t, D).transpose(0, 2, 1, 3)

    o = _flash_core(to_bh(q), to_bh(k), to_bh(v), scale, causal,
                    block_q, block_k, interpret, rep, window or 0)
    return from_bh(o, Tq)


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6, 7, 8, 9))
def _flash_core(q, k, v, scale, causal, block_q, block_k, interpret, rep,
                window):
    o, _ = _fwd_impl(q, k, v, scale, causal, block_q, block_k, interpret,
                     rep, window)
    return o


def _flash_fwd(q, k, v, scale, causal, block_q, block_k, interpret, rep,
               window):
    o, lse = _fwd_impl(q, k, v, scale, causal, block_q, block_k, interpret,
                       rep, window)
    return o, (q, k, v, o, lse)


def _flash_bwd(scale, causal, block_q, block_k, interpret, rep, window,
               res, do):
    q, k, v, o, lse = res
    delta = jnp.sum(do.astype(jnp.float32) * o.astype(jnp.float32),
                    axis=-1)                                 # [BH, Tq]
    # The backward kernels dot do against v/q — same raw-dtype contract
    # as the forward (an f32 cotangent over bf16 primals is legal in jax).
    do = do.astype(q.dtype)
    return _bwd_impl(q, k, v, do, lse, delta, scale=scale, causal=causal,
                     block_q=block_q, block_k=block_k, interpret=interpret,
                     rep=rep, window=window)


def _bwd_impl(q, k, v, do, lse, delta, *, scale, causal, block_q, block_k,
              interpret, rep=1, window=0):
    """Flash backward over one (q-shard, kv-shard) pair: q/do [BH, Tq, D],
    k/v [BK, Tk, D], lse/delta [BH, Tq] (lse may be the GLOBAL logsumexp —
    that is exactly what makes this reusable as one ring-attention backward
    step) -> (dq, dk, dv) in the input dtypes."""
    BH, Tq, D = q.shape
    BK = k.shape[0]
    Tk = k.shape[1]
    bq, bk = min(block_q, Tq), min(block_k, Tk)

    qp, dop = _pad_t(q, bq), _pad_t(do, bq)
    kp, vp = _pad_t(k, bk), _pad_t(v, bk)
    pad_q = qp.shape[1] - Tq
    # Pad with 0 (see the forward's empty-row sentinel): padded rows then
    # produce p = exp(NEG_INF - 0) = 0 and contribute nothing.  3D
    # [BH, T, 1] for the same block-shape rule as the forward's lse.
    lsep = jnp.pad(lse, ((0, 0), (0, pad_q)))[..., None]
    deltap = jnp.pad(delta, ((0, 0), (0, pad_q)))[..., None]
    Tqp, Tkp = qp.shape[1], kp.shape[1]
    n_q, n_k = Tqp // bq, Tkp // bk

    dq = pl.pallas_call(
        functools.partial(_dq_kernel, scale=scale, causal=causal,
                          block_q=bq, block_k=bk, n_k=n_k,
                          tq_valid=Tq, tk_valid=Tk, window=window),
        grid=(BH, n_q, n_k),
        in_specs=[
            pl.BlockSpec((1, bq, D), lambda b, i, j: (b, i, 0)),
            pl.BlockSpec((1, bk, D), lambda b, i, j: (b // rep, j, 0)),
            pl.BlockSpec((1, bk, D), lambda b, i, j: (b // rep, j, 0)),
            pl.BlockSpec((1, bq, D), lambda b, i, j: (b, i, 0)),
            pl.BlockSpec((1, bq, 1), lambda b, i, j: (b, i, 0)),
            pl.BlockSpec((1, bq, 1), lambda b, i, j: (b, i, 0)),
        ],
        out_specs=pl.BlockSpec((1, bq, D), lambda b, i, j: (b, i, 0)),
        out_shape=jax.ShapeDtypeStruct((BH, Tqp, D), q.dtype),
        scratch_shapes=[pltpu.VMEM((bq, D), jnp.float32)],
        compiler_params=tpu_compiler_params(
            dimension_semantics=("parallel", "parallel", "arbitrary")),
        interpret=interpret,
    )(qp, kp, vp, dop, lsep, deltap)[:, :Tq]

    # dk/dv accumulate over the rep q-heads sharing each kv head: grid is
    # (B*K, n_k, rep*n_q) and the q-side index map walks head r = t // n_q,
    # block qi = t % n_q of the kv head's group.
    def _qix(b, j, t):
        return (b * rep + t // n_q, t % n_q, 0)

    dk, dv = pl.pallas_call(
        functools.partial(_dkv_kernel, scale=scale, causal=causal,
                          block_q=bq, block_k=bk, n_q=n_q, n_t=rep * n_q,
                          tq_valid=Tq, tk_valid=Tk, window=window),
        grid=(BK, n_k, rep * n_q),
        in_specs=[
            pl.BlockSpec((1, bq, D), _qix),
            pl.BlockSpec((1, bk, D), lambda b, j, t: (b, j, 0)),
            pl.BlockSpec((1, bk, D), lambda b, j, t: (b, j, 0)),
            pl.BlockSpec((1, bq, D), _qix),
            pl.BlockSpec((1, bq, 1), _qix),
            pl.BlockSpec((1, bq, 1), _qix),
        ],
        out_specs=[
            pl.BlockSpec((1, bk, D), lambda b, j, i: (b, j, 0)),
            pl.BlockSpec((1, bk, D), lambda b, j, i: (b, j, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((BK, Tkp, D), k.dtype),
            jax.ShapeDtypeStruct((BK, Tkp, D), v.dtype),
        ],
        scratch_shapes=[pltpu.VMEM((bk, D), jnp.float32),
                        pltpu.VMEM((bk, D), jnp.float32)],
        compiler_params=tpu_compiler_params(
            dimension_semantics=("parallel", "parallel", "arbitrary")),
        interpret=interpret,
    )(qp, kp, vp, dop, lsep, deltap)
    return dq, dk[:, :Tk], dv[:, :Tk]


_flash_core.defvjp(_flash_fwd, _flash_bwd)
