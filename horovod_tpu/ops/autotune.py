"""Online autotuning of fusion threshold and cycle time.

Parity: the reference's parameter manager (``horovod/common/
parameter_manager.cc`` — SURVEY.md §2a N9): warmup discard, scored samples
(bytes reduced per second), *online search* over the continuous
(fusion-threshold, cycle-time) space — the reference uses Bayesian
optimization; here it is coordinate descent in log-space with
multiplicative step decay, which reaches any regime from any start (a 3×3
multiplier grid around a bad starting point cannot), converges in tens of
samples, and needs no GP machinery.  ``HOROVOD_AUTOTUNE`` /
``HOROVOD_AUTOTUNE_LOG`` surface.

Distributed consistency (TPU-native redesign of the reference's
coordinator-broadcast): the sample *cadence* is a pure function of the
work-cycle count — identical on every rank because negotiated batches are
identical — so every rank reaches each sample boundary together and
enqueues the same agreement broadcast.  Rank 0 feeds ITS score to the
search and broadcasts the next candidate ``[threshold, cycle, done]``
through the engine's own collective path; all ranks apply the payload, so
parameters never diverge even though per-rank timings do.
"""

from __future__ import annotations

import math
import time
from typing import Callable, List, Optional, Sequence, Tuple

import numpy as np

# Search bounds (log2-space), matching the reference's explored ranges:
# fusion 1KB..1GB, cycle 0.1ms..100ms.
_THR_BOUNDS = (10.0, 30.0)          # 2^10 = 1KB .. 2^30 = 1GB
_CYC_BOUNDS = (math.log2(1e-4), math.log2(0.1))
# Response-cache capacity (client-side slot budget), lower bound 16: too
# small churns the steady-state bitvector path back to full announces.  The
# upper bound is the server's configured capacity (the client can't ride
# more slots than the server assigns — anything above it is a dead knob).
_CAP_LO = 4.0
# Pipeline coordinates (multi-process only, like the cache coordinate):
# fused-reduce chunk size 64KB..1GB — below 64KB per-chunk collective
# overhead always dominates; in-flight window 1..8 fused batches (log2
# space, rounded to an integer on apply).
_CHUNK_BOUNDS = (16.0, 30.0)
_INFLIGHT_BOUNDS = (0.0, 3.0)
# Latency fast-lane threshold (multi-process only, same gate): 256B..16MB.
# The left end of the busbw curve is where the fusion buffer costs more
# than it buys (BENCH_SELF_r03/r05) — the search finds the crossover
# instead of a hand-set constant.  Note cycle_time is ALREADY the second
# base coordinate, so the latency pair (fast_lane_threshold, cycle_time)
# is fully searched, never hand-set.
_FAST_LANE_BOUNDS = (8.0, 24.0)
# Hierarchical crossover threshold (two-level ICI/DCN allreduce, armed via
# HOROVOD_HIERARCHICAL_ALLREDUCE): 1KB..256MB.  Below the crossover a flat
# ring's single launch beats the three-leg pipeline's fixed cost; above it
# the ~1/local_size cross-slice byte saving wins.  The crossover depends on
# the DCN:ICI bandwidth ratio of the actual pod, so it is searched, not
# hand-set.  Walking the knob only flips per-batch decisions (fusion-key
# re-keyed, never in the negotiation digest), so moves are control-plane
# free — the same zero-traffic rule as HOROVOD_PIPELINE_CHUNK.
_HIER_THR_BOUNDS = (10.0, 28.0)
# Zero-RTT pair (protocol v7, multi-process only).  spec_ready_after
# 1..32 consecutive ready-on-first-announce rounds before the coordinator
# predicts (small = aggressive speculation, large = conservative; 0 — the
# explicit opt-out — gates the coordinate off entirely, like the cache
# knob).  round_pipeline 1..4 in-flight negotiation rounds per client.
_SPEC_BOUNDS = (0.0, 5.0)
_RPIPE_BOUNDS = (0.0, 2.0)
# Checkpoint-lane pair (ISSUE 15, closing the ISSUE 14 carry-over) —
# gated on the state plane being armed (HOROVOD_CKPT_DIR): shard-chunk
# size 64KB..64MB (smaller chunks interleave more finely with gradient
# cycles but pay more dispatches; bigger chunks stall the cycle tail
# longer), lane budget 1..8 chunks per engine cycle.  Neither knob can
# change gradient dispatch order (the budget rule is lane-guarded), so
# walking them trades ONLY commit latency against cycle-tail time.
_CKPT_CHUNK_BOUNDS = (16.0, 26.0)
_CKPT_BUDGET_BOUNDS = (0.0, 3.0)


def _clamp(v: float, lo: float, hi: float) -> float:
    return min(max(v, lo), hi)


class LogCoordinateDescent:
    """Coordinate descent over log2-space points with step decay.

    Protocol: call :meth:`proposal` for the point to measure next, then
    :meth:`record` with its score.  The first evaluation scores the
    starting point; each later one either accepts (continue along the
    winning direction) or moves on (opposite direction → next coordinate →
    sweep end).  A sweep with no accepted move halves both steps; the
    search finishes when steps drop under ``min_step`` (≈ a 1.09× factor
    for 0.125 in log2) or ``max_evals`` is spent.
    """

    def __init__(self, start: Sequence[float],
                 bounds: Sequence[Tuple[float, float]],
                 init_step: float = 2.0, min_step: float = 0.125,
                 rel_gain: float = 0.02, max_evals: int = 48):
        self.point = [_clamp(p, *b) for p, b in zip(start, bounds)]
        self.bounds = list(bounds)
        self.step = [init_step] * len(self.point)
        self.min_step = min_step
        self.rel_gain = rel_gain
        self.max_evals = max_evals
        self.evals = 0
        self.best_score: Optional[float] = None
        self._coord = 0
        self._dir = +1
        self._accepted_on_line = False
        self._improved_in_sweep = False
        self._pending: Optional[List[float]] = list(self.point)
        self.done = False

    def proposal(self) -> Tuple[float, ...]:
        return tuple(self._pending if self._pending is not None
                     else self.point)

    def record(self, score: float):
        """Consume the score of the current proposal; advance the search."""
        if self.done:
            return
        self.evals += 1
        if self.best_score is None:
            # Baseline: score of the starting point.
            self.best_score = score
        elif (score > self.best_score * (1.0 + self.rel_gain)
              and self._pending is not None):
            self.point = list(self._pending)
            self.best_score = score
            self._accepted_on_line = True
            self._improved_in_sweep = True
        else:
            self._turn()
        if self.evals >= self.max_evals:
            self.done = True
            self._pending = None
            return
        self._propose_next()

    # ------------------------------------------------------------ internals
    def _turn(self):
        """Current line is exhausted: flip direction or advance coordinate."""
        if self._dir == +1 and not self._accepted_on_line:
            self._dir = -1
            return
        self._next_coord()

    def _next_coord(self):
        self._dir = +1
        self._accepted_on_line = False
        self._coord += 1
        if self._coord >= len(self.point):
            self._coord = 0
            if not self._improved_in_sweep:
                self.step = [s * 0.5 for s in self.step]
                if max(self.step) < self.min_step:
                    self.done = True
            self._improved_in_sweep = False

    def _propose_next(self):
        """Find the next in-bounds candidate distinct from the current
        point; skipped (clamped-away) lines count as exhausted."""
        if self.done:
            self._pending = None
            return
        for _ in range(2 * len(self.point) + 1):
            cand = list(self.point)
            c = self._coord
            cand[c] = _clamp(cand[c] + self._dir * self.step[c],
                             *self.bounds[c])
            if abs(cand[c] - self.point[c]) > 1e-12:
                self._pending = cand
                return
            # Clamped onto the current point: this direction is a wall.
            if self._dir == +1 and not self._accepted_on_line:
                self._dir = -1
            else:
                self._next_coord()
                if self.done:
                    self._pending = None
                    return
        # Every direction is a wall at this step size — decay and retry.
        self.step = [s * 0.5 for s in self.step]
        if max(self.step) < self.min_step:
            self.done = True
            self._pending = None
        else:
            self._propose_next()


class ParameterManager:
    """Engine-side sampling loop + distributed agreement around the search.

    ``broadcaster(payload) -> handle`` and ``poller(handle) -> payload|None``
    are injectable for unit tests; the defaults ride the engine's own
    eager broadcast (root 0), exactly like the final-pick agreement the
    grid version used — but now EVERY move is agreed, so ranks never
    diverge mid-search.
    """

    def __init__(self, engine, warmup_samples: int = 3,
                 steps_per_sample: int = 10, log_path: str = "",
                 clock: Optional[Callable[[], float]] = None,
                 broadcaster=None, poller=None, max_evals: int = 48):
        self._engine = engine
        self._warmup_remaining = warmup_samples
        self._steps_per_sample = steps_per_sample
        self._log_path = log_path
        self._clock = clock or time.monotonic
        self._broadcaster = broadcaster or self._engine_broadcast
        self._poller = poller or self._engine_poll

        thr0 = max(float(engine.fusion_threshold), 1024.0)
        cyc0 = max(float(engine.cycle_time_s), 1e-4)
        starts = [math.log2(thr0), math.log2(cyc0)]
        bounds = [_THR_BOUNDS, _CYC_BOUNDS]
        # Third tunable — negotiation response-cache capacity — only when
        # a multi-process controller exists (single-controller mode has no
        # negotiation) AND the cache is enabled (capacity 0 is an explicit
        # opt-out: tuning a dead knob would waste a third of the eval
        # budget).  Every rank takes the same branch (same env config), so
        # the agreement payload shape is consistent.
        ctl = getattr(engine, "controller", None)
        self._tune_cache = ctl is not None and getattr(ctl, "cache_enabled",
                                                       False)
        if self._tune_cache:
            # The config capacity is both the starting point and the upper
            # bound: the rank-0 server's slot table was sized from the same
            # config, so larger client budgets cannot increase coverage.
            cap0 = max(float(ctl.cache_capacity), 16.0)
            starts.append(math.log2(cap0))
            bounds.append((_CAP_LO, max(_CAP_LO + 1.0, math.log2(cap0))))
        # Pipeline coordinates — gated exactly like the cache coordinate
        # (multi-process only): chunking/in-flight only matter where a
        # negotiation round exists to overlap, and single-controller runs
        # must not waste eval budget on dead knobs.  Every rank reads the
        # same engine config, so the agreement payload shape matches.
        self._tune_pipeline = ctl is not None
        if self._tune_pipeline:
            chunk0 = max(float(engine.pipeline_chunk_bytes
                               or engine.fusion_threshold), 1024.0)
            starts.append(math.log2(chunk0))
            bounds.append(_CHUNK_BOUNDS)
            starts.append(math.log2(max(float(engine.max_inflight), 1.0)))
            bounds.append(_INFLIGHT_BOUNDS)
        # Sixth coordinate — the latency fast-lane threshold — gated like
        # the pipeline pair: the fast lane's win (skipping the fusion
        # buffer + per-cycle key construction) only exists where a
        # negotiation round and the slot-pinned program path exist.
        # Moves broadcast through the same agreement payload, so the
        # threshold can never diverge across ranks (divergence would fork
        # the batch plan).
        self._tune_fast_lane = ctl is not None
        if self._tune_fast_lane:
            fl0 = max(float(engine.fast_lane_threshold) or 4096.0, 256.0)
            starts.append(math.log2(fl0))
            bounds.append(_FAST_LANE_BOUNDS)
        # Hierarchical crossover coordinate — gated on the two-level mode
        # being ARMED (HOROVOD_HIERARCHICAL_ALLREDUCE is fleet-uniform
        # config, so every rank takes the same branch): with the mode off
        # every batch dispatches flat regardless of the threshold, and
        # tuning a dead knob would waste eval budget.  Moves ride the same
        # agreement broadcast, so the per-batch flat-vs-hier decision (a
        # fusion-key input — batching must stay rank-invariant, HVD110)
        # can never diverge across ranks.
        self._tune_hier = (ctl is not None
                           and getattr(engine, "hierarchical_allreduce",
                                       False))
        if self._tune_hier:
            ht0 = max(float(engine.hier_threshold_bytes) or 65536.0, 1024.0)
            starts.append(math.log2(ht0))
            bounds.append(_HIER_THR_BOUNDS)
        # Zero-RTT pair (protocol v7) — spec_ready_after gated like the
        # cache coordinate (speculation off is an explicit opt-out, and
        # the server's streak threshold was fixed at start from the same
        # config: the client-side knob gates prediction CONSUMPTION, so
        # walking it trades speculation eagerness against mispredict
        # fallbacks); round_pipeline gated like the pipeline pair.  Moves
        # ride the same agreement broadcast, so the in-flight windows can
        # never diverge across ranks.
        self._tune_spec = (ctl is not None
                           and getattr(ctl, "spec_ready_after", 0) > 0)
        if self._tune_spec:
            sp0 = max(float(ctl.spec_ready_after), 1.0)
            starts.append(math.log2(sp0))
            bounds.append(_SPEC_BOUNDS)
        self._tune_round_pipeline = ctl is not None
        if self._tune_round_pipeline:
            rp0 = max(float(getattr(ctl, "round_pipeline", 1)), 1.0)
            starts.append(math.log2(rp0))
            bounds.append(_RPIPE_BOUNDS)
        # Checkpoint-lane pair — gated on the state plane being ARMED
        # (HOROVOD_CKPT_DIR is fleet-uniform config, so every rank takes
        # the same branch and the agreement payload shape matches):
        # tuning the chunk/budget knobs with no durability stream would
        # waste eval budget on dead coordinates.
        self._tune_ckpt = getattr(engine, "stateplane", None) is not None
        if self._tune_ckpt:
            ck0 = max(float(engine.stateplane.chunk_bytes), 1024.0)
            starts.append(math.log2(ck0))
            bounds.append(_CKPT_CHUNK_BOUNDS)
            starts.append(math.log2(
                max(float(engine.ckpt_lane_budget), 1.0)))
            bounds.append(_CKPT_BUDGET_BOUNDS)
        self.search = LogCoordinateDescent(
            start=tuple(starts), bounds=tuple(bounds), max_evals=max_evals)
        self._sample_no = 0
        self._cycles_in_sample = 0
        self._bytes_in_sample = 0
        self._sample_start = self._clock()
        self._move_handle = None
        self.tuning = True
        self._log_header_written = False

    # ------------------------------------------------------------ schedule
    def on_cycle(self, nbytes: int):
        """Called by the engine after every cycle that processed work."""
        if not self.tuning or nbytes <= 0:
            return
        if self._move_handle is not None:
            self._poll_move()
            return
        self._cycles_in_sample += 1
        self._bytes_in_sample += nbytes
        if self._cycles_in_sample < self._steps_per_sample:
            return

        elapsed = max(self._clock() - self._sample_start, 1e-9)
        score = self._bytes_in_sample / elapsed
        self._cycles_in_sample = 0
        self._bytes_in_sample = 0
        if self._warmup_remaining > 0:
            self._warmup_remaining -= 1
            self._sample_start = self._clock()
            return

        # Rank 0's search consumes rank 0's score; other ranks run the
        # same code on their local score but their proposals are
        # overwritten by the agreement broadcast, so only the CADENCE
        # (score-independent) must match across ranks — and it does.
        measured = self.search.proposal()
        self.search.record(score)
        self._log_sample(measured, score)
        point = self.search.point if self.search.done \
            else self.search.proposal()
        params = [2.0 ** p for p in point]
        payload = np.asarray(params + [1.0 if self.search.done else 0.0],
                             np.float64)
        self._move_handle = self._broadcaster(payload)
        self._sample_no += 1

    def _apply_params(self, params):
        self._engine.fusion_threshold = int(params[0])
        self._engine.cycle_time_s = float(params[1])
        idx = 2
        if self._tune_cache and len(params) > idx:
            # Client-side slot budget: shrinking trims LRU slots (safe —
            # a dropped slot simply full-announces and relearns), growing
            # lets more tuples ride the bitvector.
            self._engine.controller.cache_capacity = max(1, int(params[idx]))
            idx += 1
        if self._tune_pipeline and len(params) > idx + 1:
            # Chunk plans re-key the program cache by COUNT, so walking
            # this knob recompiles at most once per distinct plan; the
            # in-flight bound applies from the next dispatch (the ring
            # reads its depth live).
            self._engine.pipeline_chunk_bytes = int(params[idx])
            self._engine.max_inflight = max(1, int(round(params[idx + 1])))
            idx += 2
        if self._tune_fast_lane and len(params) > idx:
            # Applies from the next ready verdict; stale fast-lane pins
            # self-invalidate on their validity compare.
            self._engine.fast_lane_threshold = int(params[idx])
            idx += 1
        if self._tune_hier and len(params) > idx:
            # Applies from the next batch's _hier_decision; the program
            # cache and slot pins re-key on the per-batch DECISION (not
            # the raw threshold), so walking it recompiles at most one
            # program per (shape, mode) pair and stale pins self-
            # invalidate on their validity compare.
            self._engine.hier_threshold_bytes = max(0, int(params[idx]))
            idx += 1
        if self._tune_spec and len(params) > idx:
            # Client-side consumption gate: never moves to 0 (the bounds
            # start at 1) — 0 is the config-level opt-out that disables
            # the coordinate entirely.
            self._engine.controller.spec_ready_after = max(
                1, int(round(params[idx])))
            idx += 1
        if self._tune_round_pipeline and len(params) > idx:
            # Applies from the next round: a shrunk window drains
            # naturally at the next _round's entry drain.
            self._engine.controller.round_pipeline = max(
                1, int(round(params[idx])))
            idx += 1
        if self._tune_ckpt and len(params) > idx + 1 \
                and getattr(self._engine, "stateplane", None) is not None:
            # Applies from the next commit's write job (chunk plans are
            # per-epoch) and the next cycle's tail pop (the budget is
            # read live); gradient dispatch order is invariant to both.
            self._engine.stateplane.chunk_bytes = max(1, int(params[idx]))
            self._engine.ckpt_lane_budget = max(
                1, int(round(params[idx + 1])))

    def _poll_move(self):
        payload = self._poller(self._move_handle)
        if payload is None:
            return
        self._move_handle = None
        try:
            values = [float(x) for x in np.asarray(payload).reshape(-1)]
            params, done = values[:-1], values[-1]
            if len(params) < 2:
                raise ValueError("short payload")
        except Exception:  # pragma: no cover - never break training
            params = [2.0 ** p for p in self.search.point]
            done = 1.0
        self._apply_params(params)
        if done >= 0.5:
            self.tuning = False
            extra = ""
            idx = 2
            if self._tune_cache and len(params) > idx:
                extra += f" response_cache_capacity={int(params[idx])}"
                idx += 1
            if self._tune_pipeline and len(params) > idx + 1:
                extra += (f" pipeline_chunk_bytes={int(params[idx])}"
                          f" max_inflight="
                          f"{max(1, int(round(params[idx + 1])))}")
                idx += 2
            if self._tune_fast_lane and len(params) > idx:
                extra += f" fast_lane_threshold={int(params[idx])}"
                idx += 1
            if self._tune_hier and len(params) > idx:
                extra += f" hier_threshold_bytes={int(params[idx])}"
                idx += 1
            if self._tune_spec and len(params) > idx:
                extra += (f" spec_ready_after="
                          f"{max(1, int(round(params[idx])))}")
                idx += 1
            if self._tune_round_pipeline and len(params) > idx:
                extra += (f" round_pipeline="
                          f"{max(1, int(round(params[idx])))}")
                idx += 1
            if self._tune_ckpt and len(params) > idx + 1:
                extra += (f" ckpt_chunk_bytes={int(params[idx])}"
                          f" ckpt_lane_budget="
                          f"{max(1, int(round(params[idx + 1])))}")
            self._log_line(f"# final: fusion_threshold={int(params[0])} "
                           f"cycle_time_s={params[1]:.6f}{extra} "
                           f"evals={self.search.evals}\n")
        self._sample_start = self._clock()

    # ----------------------------------------------------- engine transport
    def _engine_broadcast(self, payload: np.ndarray):
        from . import eager
        try:
            contrib = (payload if eager.per_process_mode()
                       else eager.replicated(payload))
            return eager.broadcast_async(
                contrib, root_rank=0,
                name=f"__autotune.move.{self._sample_no}")
        except Exception:  # pragma: no cover - never break training
            return ("local", payload)

    def _engine_poll(self, handle):
        from . import eager
        if isinstance(handle, tuple) and handle[0] == "local":
            return handle[1]
        if not eager.poll(handle):
            return None
        try:
            return np.asarray(eager.to_local(eager.synchronize(handle)))
        except Exception:  # pragma: no cover - never break training
            return np.asarray([2.0 ** self.search.point[0],
                               2.0 ** self.search.point[1], 1.0])

    # ------------------------------------------------------------- logging
    def _log_sample(self, measured, score: float):
        if not self._log_header_written:
            cols = ""
            if self._tune_cache:
                cols += ",response_cache_capacity"
            if self._tune_pipeline:
                cols += ",pipeline_chunk_bytes,max_inflight"
            if self._tune_fast_lane:
                cols += ",fast_lane_threshold"
            if self._tune_hier:
                cols += ",hier_threshold_bytes"
            if self._tune_spec:
                cols += ",spec_ready_after"
            if self._tune_round_pipeline:
                cols += ",round_pipeline"
            if self._tune_ckpt:
                cols += ",ckpt_chunk_bytes,ckpt_lane_budget"
            self._log_line(f"sample,fusion_threshold_bytes,cycle_time_s"
                           f"{cols},score_bytes_per_s\n")
            self._log_header_written = True
        params = [2.0 ** p for p in measured]
        extra = ""
        idx = 2
        if self._tune_cache and len(params) > idx:
            extra += f",{int(params[idx])}"
            idx += 1
        if self._tune_pipeline and len(params) > idx + 1:
            extra += (f",{int(params[idx])}"
                      f",{max(1, int(round(params[idx + 1])))}")
            idx += 2
        if self._tune_fast_lane and len(params) > idx:
            extra += f",{int(params[idx])}"
            idx += 1
        if self._tune_hier and len(params) > idx:
            extra += f",{int(params[idx])}"
            idx += 1
        if self._tune_spec and len(params) > idx:
            extra += f",{max(1, int(round(params[idx])))}"
            idx += 1
        if self._tune_round_pipeline and len(params) > idx:
            extra += f",{max(1, int(round(params[idx])))}"
            idx += 1
        if self._tune_ckpt and len(params) > idx + 1:
            extra += (f",{int(params[idx])}"
                      f",{max(1, int(round(params[idx + 1])))}")
        self._log_line(f"{self._sample_no},{int(params[0])},"
                       f"{params[1]:.6f}{extra},{score:.1f}\n")

    def _log_line(self, line: str):
        if not self._log_path:
            return
        try:
            with open(self._log_path, "a") as fh:
                fh.write(line)
        except OSError:  # pragma: no cover
            pass
