"""Online autotuning of fusion threshold and cycle time.

Parity: the reference's parameter manager (``horovod/common/
parameter_manager.cc`` — SURVEY.md §2a N9): warmup discard, scored samples
(bytes reduced per second), exploration of the (fusion-threshold,
cycle-time) space, ``HOROVOD_AUTOTUNE`` / ``HOROVOD_AUTOTUNE_LOG`` surface.

TPU-native redesign of the distributed-consistency problem: the reference
broadcasts every parameter update from the coordinator.  Here the
exploration *schedule* is a pure function of the work-cycle count — which is
identical on every rank because negotiated batches are identical — so ranks
walk the same candidate at the same cycle with no extra messages.  Only the
FINAL pick depends on per-rank timing, so that one decision is agreed by
broadcasting rank 0's choice through the engine's own collective path.
"""

from __future__ import annotations

import time
from typing import Callable, List, Optional, Tuple

import numpy as np

# Log-space multipliers explored around the configured starting point
# (reference explores fusion 0..64MB and cycle 1..100ms in similar fashion).
_THRESHOLD_MULTIPLIERS = (0.25, 1.0, 4.0)
_CYCLE_MULTIPLIERS = (0.2, 1.0, 5.0)


class ParameterManager:
    def __init__(self, engine, warmup_samples: int = 3,
                 steps_per_sample: int = 10, log_path: str = "",
                 clock: Optional[Callable[[], float]] = None):
        self._engine = engine
        self._warmup_remaining = warmup_samples
        self._steps_per_sample = steps_per_sample
        self._log_path = log_path
        self._clock = clock or time.monotonic

        base_thr = float(engine.fusion_threshold)
        base_cyc = float(engine.cycle_time_s)
        self._candidates: List[Tuple[float, float]] = [
            (max(1024.0, base_thr * tm), max(1e-4, base_cyc * cm))
            for tm in _THRESHOLD_MULTIPLIERS for cm in _CYCLE_MULTIPLIERS]
        self._scores: List[float] = []
        self._sample_idx = -1          # -1 while warming up
        self._cycles_in_sample = 0
        self._bytes_in_sample = 0
        self._sample_start = self._clock()
        self._finalize_handle: Optional[int] = None
        self.tuning = True
        self._log_header_written = False

    # ------------------------------------------------------------ schedule
    def on_cycle(self, nbytes: int):
        """Called by the engine after every cycle that processed work."""
        if not self.tuning or nbytes <= 0:
            return
        if self._finalize_handle is not None:
            self._poll_finalize()
            return
        self._cycles_in_sample += 1
        self._bytes_in_sample += nbytes
        if self._cycles_in_sample < self._steps_per_sample:
            return

        elapsed = max(self._clock() - self._sample_start, 1e-9)
        score = self._bytes_in_sample / elapsed
        if self._warmup_remaining > 0:
            self._warmup_remaining -= 1
        else:
            if self._sample_idx >= 0:
                self._scores.append(score)
                self._log_sample(score)
            self._sample_idx += 1
            if self._sample_idx < len(self._candidates):
                thr, cyc = self._candidates[self._sample_idx]
                self._engine.fusion_threshold = int(thr)
                self._engine.cycle_time_s = cyc
            else:
                self._begin_finalize()
        self._cycles_in_sample = 0
        self._bytes_in_sample = 0
        self._sample_start = self._clock()

    # ------------------------------------------------------------ finalize
    def _local_best(self) -> Tuple[float, float]:
        best = int(np.argmax(self._scores)) if self._scores else 0
        return self._candidates[best]

    def _begin_finalize(self):
        """Agree on rank 0's winner via the engine's own broadcast path."""
        thr, cyc = self._local_best()
        from . import eager
        try:
            value = np.asarray([thr, cyc], np.float64)
            contrib = (value if eager.per_process_mode()
                       else eager.replicated(value))
            self._finalize_handle = eager.broadcast_async(
                contrib, root_rank=0, name="__autotune.final")
        except Exception:  # pragma: no cover - never break training
            self._apply_final(thr, cyc)

    def _poll_finalize(self):
        from . import eager
        if not eager.poll(self._finalize_handle):
            return
        try:
            out = np.asarray(eager.to_local(
                eager.synchronize(self._finalize_handle)))
            self._apply_final(float(out.reshape(-1)[0]),
                              float(out.reshape(-1)[1]))
        except Exception:  # pragma: no cover - never break training
            thr, cyc = self._local_best()
            self._apply_final(thr, cyc)
        finally:
            self._finalize_handle = None

    def _apply_final(self, thr: float, cyc: float):
        # The agreement broadcast rides f32 arrays; snap back to the exact
        # candidate so every rank lands on identical parameters.
        thr, cyc = min(self._candidates,
                       key=lambda c: abs(c[0] - thr) / c[0]
                       + abs(c[1] - cyc) / c[1])
        self._engine.fusion_threshold = int(thr)
        self._engine.cycle_time_s = cyc
        self.tuning = False
        self._log_line(f"# final: fusion_threshold={int(thr)} "
                       f"cycle_time_s={cyc:.6f}\n")

    # ------------------------------------------------------------- logging
    def _log_sample(self, score: float):
        thr, cyc = self._candidates[self._sample_idx] \
            if self._sample_idx < len(self._candidates) else self._local_best()
        if not self._log_header_written:
            self._log_line("sample,fusion_threshold_bytes,cycle_time_s,"
                           "score_bytes_per_s\n")
            self._log_header_written = True
        self._log_line(f"{self._sample_idx},{int(thr)},{cyc:.6f},"
                       f"{score:.1f}\n")

    def _log_line(self, line: str):
        if not self._log_path:
            return
        try:
            with open(self._log_path, "a") as fh:
                fh.write(line)
        except OSError:  # pragma: no cover
            pass
